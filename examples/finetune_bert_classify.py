"""BERT sequence-classification fine-tune: pooled [CLS] + task head, one
compiled TrainStep, hapi-style loop on synthetic data.

    JAX_PLATFORMS=cpu python examples/finetune_bert_classify.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models import BertConfig, BertModel


class BertClassifier(nn.Layer):
    def __init__(self, cfg, num_classes):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = nn.Dropout(0.1)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, ids):
        _, pooled = self.bert(ids)  # (sequence, tanh-pooled [CLS])
        return self.classifier(self.dropout(pooled))


def main():
    paddle.seed(0)
    cfg = BertConfig.tiny()
    model = BertClassifier(cfg, num_classes=4)
    opt = paddle.optimizer.AdamW(learning_rate=5e-4,
                                 parameters=model.parameters())

    def loss_fn(ids, labels):
        return paddle.nn.functional.cross_entropy(model(ids), labels)

    step = paddle.jit.TrainStep(model, loss_fn, opt)

    # synthetic "sentences": the label is recoverable from the token stats
    rng = np.random.RandomState(0)
    n, seqlen = 256, 24
    labels = rng.randint(0, 4, n)
    ids = rng.randint(4, cfg.vocab_size, (n, seqlen))
    ids[np.arange(n), 1] = labels  # plant the signal
    ids, labels = ids.astype(np.int32), labels.astype(np.int64)

    for epoch in range(4):
        perm = rng.permutation(n)
        tot = 0.0
        for i in range(0, n, 32):
            b = perm[i:i + 32]
            loss = step(paddle.to_tensor(ids[b]), paddle.to_tensor(labels[b]))
            tot += float(loss.item())
        print(f"epoch {epoch}  loss {tot / (n // 32):.4f}")

    model.eval()
    logits = model(paddle.to_tensor(ids[:64]))
    acc = (np.asarray(logits._value).argmax(-1) == labels[:64]).mean()
    print(f"train-set accuracy: {acc:.2f}")
    assert acc > 0.9, "the planted signal should be learnable"


if __name__ == "__main__":
    main()
