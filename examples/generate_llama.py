"""Compiled text generation: prefill + decode scan in one XLA program.

    python examples/generate_llama.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(tensor_parallel=False,
                                              use_flash_attention=False))
    model.eval()
    prompt = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 1000, (2, 8), np.int32))
    greedy = model.generate(prompt, max_new_tokens=16)
    sampled = model.generate(prompt, max_new_tokens=16, do_sample=True,
                             temperature=0.8, top_p=0.9)
    print("greedy :", np.asarray(greedy._value))
    print("sampled:", np.asarray(sampled._value))


if __name__ == "__main__":
    main()
