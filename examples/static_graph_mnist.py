"""The reference's canonical static-graph workflow, unmodified (ref
executor.py:1104 docs): program_guard capture -> per-batch Executor.run ->
save_inference_model -> serve with paddle.inference.

    JAX_PLATFORMS=cpu python examples/static_graph_mnist.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.static as static


def main():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    # synthetic MNIST-shaped data
    xs = rng.randn(512, 784).astype(np.float32)
    ys = rng.randint(0, 10, (512, 1)).astype(np.int64)

    main_prog, startup = static.Program(), static.Program()
    with static.program_guard(main_prog, startup):
        x = static.data("img", [None, 784], "float32")
        y = static.data("label", [None, 1], "int64")
        h = static.nn.fc(x, size=128, activation="relu", name="fc1")
        logits = static.nn.fc(h, size=10, name="fc2")
        loss = paddle.mean(paddle.nn.functional.cross_entropy(logits, y))
        paddle.optimizer.Adam(learning_rate=1e-3).minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    for step in range(30):
        i = (step * 64) % 512
        lv, = exe.run(main_prog,
                      feed={"img": xs[i:i + 64], "label": ys[i:i + 64]},
                      fetch_list=[loss])
        if step % 10 == 0:
            print(f"step {step:3d}  loss {float(lv):.4f}")

    # export the inference graph (batch-polymorphic) and serve it
    prefix = "/tmp/static_mnist/model"
    static.save_inference_model(prefix, [x], [logits], exe)
    from paddle_tpu import inference as infer

    pred = infer.create_predictor(infer.Config(prefix))
    probs, = pred.run([xs[:5]])
    print("served logits shape:", probs.shape)

    # concurrent serving: clones for threads, micro-batching for requests
    batcher = infer.DynamicBatcher(pred.clone(), max_batch_size=64,
                                   timeout_ms=5)
    futs = [batcher.submit(xs[i:i + 1]) for i in range(8)]
    outs = [f.result()[0] for f in futs]
    batcher.close()
    print("micro-batched", len(outs), "requests, each ->", outs[0].shape)


if __name__ == "__main__":
    main()
