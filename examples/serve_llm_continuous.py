"""Continuous-batching LLM serving with paddle_tpu.inference.LLMEngine.

Run (CPU works; on TPU use a real checkpoint via model.set_state_dict):

    python examples/serve_llm_continuous.py

Demonstrates: slot-pool serving with one compiled decode step for every
in-flight request, bucketed prefill admission, per-request sampling knobs,
the int8 kv-cache (half footprint + half decode stream via the Pallas
decode kernel), and chunked multi-step scheduling for high-latency hosts.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference import LLMEngine
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


def main():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(tensor_parallel=False, use_flash_attention=False,
                           max_position_embeddings=512)
    model = LlamaForCausalLM(cfg)
    model.eval()
    # production: model.bfloat16(); model.set_state_dict(paddle.load(ckpt))

    eng = LLMEngine(
        model,
        max_batch_slots=4,        # concurrent decode lanes
        max_seq_len=256,
        cache_dtype="int8",       # capacity + bandwidth lever
        prompt_buckets=(32, 64, 128),
        decode_chunk=4,           # 4 tokens per compiled call
    ).start()                     # background pump; omit and call
    #                               eng.run_until_complete() for sync use

    rng = np.random.RandomState(0)
    try:
        futures = []
        for i in range(8):  # more requests than slots: the queue drains
            prompt = rng.randint(0, cfg.vocab_size, 10 + 7 * i).astype(np.int32)
            futures.append((i, eng.submit(
                prompt,
                max_new_tokens=16,
                do_sample=(i % 2 == 1),  # per-request sampling
                temperature=0.8,
                top_p=0.95,
            )))
        for i, fut in futures:
            print(f"request {i}: {fut.result(timeout=300)}")
    finally:
        eng.stop()


if __name__ == "__main__":
    main()
