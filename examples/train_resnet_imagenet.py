"""BASELINE config #2: ResNet-50 training throughput path.

    python examples/train_resnet_imagenet.py          # synthetic data
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.vision.models import resnet50


def main():
    import jax

    on_accel = jax.default_backend() != "cpu"
    batch, img = (128, 224) if on_accel else (8, 64)

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    if on_accel:
        model.bfloat16()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    weight_decay=paddle.regularizer.L2Decay(1e-4),
                                    parameters=model.parameters())
    ce = nn.CrossEntropyLoss()

    def loss_fn(x, y):
        return ce(model(x).astype("float32"), y)

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    for it in range(5):
        x = paddle.to_tensor(rng.rand(batch, 3, img, img).astype(np.float32) * 2 - 1,
                             dtype="bfloat16" if on_accel else "float32")
        y = paddle.to_tensor(rng.randint(0, 1000, (batch,), np.int32))
        loss = step(x, y)
        print(f"step {it}: loss={float(loss.item()):.4f}")


if __name__ == "__main__":
    main()
