"""Headline benchmarks (BASELINE.md north stars).

1. LLaMA decoder pretrain step — tokens/sec/chip + MFU (BASELINE config #5 /
   ERNIE north star: >=70% MFU target on v5e, peak 197 TFLOP/s bf16).
2. ResNet-50 training throughput — images/s + MFU (BASELINE config #2).

Runs the compiled TrainStep (forward+backward+optimizer in one XLA program) in
bfloat16 on whatever accelerator is attached (the driver provides one TPU v5e chip)
and prints ONE JSON line.  The primary metric is the transformer MFU; ResNet numbers
ride along as extra fields.

vs_baseline: MFU / 0.70 (the BASELINE.md target); >1.0 beats the target.
"""
from __future__ import annotations

import json
import time

import numpy as np

V5E_PEAK_FLOPS = 197e12  # bf16, one v5e chip (nominal)

_RTT_S = 0.0  # measured dispatch+sync round-trip of the attached chip


def _measure_rtt():
    """The tunneled chip pays ~100ms dispatch+sync latency PER HOST SYNC —
    every single-sync timing window is inflated by this constant.  Measure
    it once (tiny jit call) and subtract it from every window below;
    otherwise small probes read as latency, not compute (the r2 conv
    'ceiling' of 7.5 TF/s was exactly this artifact)."""
    import time

    import jax
    import jax.numpy as jnp

    x = jnp.ones((8, 8), jnp.float32)

    @jax.jit
    def f(x):
        return x + 1.0

    _ = np.asarray(f(x))
    samples = []
    for _i in range(5):
        t0 = time.perf_counter()
        _ = np.asarray(f(x))
        samples.append(time.perf_counter() - t0)
    return sorted(samples)[len(samples) // 2]


def _measure_gemm_peak():
    """Measured bf16 gemm ceiling of the attached chip (TF/s): a 30-deep
    in-jit chain of [8192,8192]x[8192,8192] matmuls.  Context for the MFU
    number — tunneled/throttled chips deliver well below nominal peak
    (observed ~128 TF/s vs the 197 spec), so mfu_vs_measured shows how close
    the compiled step is to what this hardware can actually do."""
    import time

    import jax
    import jax.numpy as jnp

    n, iters = 8192, 30
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, n) * 0.01, jnp.bfloat16)
    w = jnp.asarray(rng.randn(n, n) * 0.01, jnp.bfloat16)

    @jax.jit
    def chain(x, w):
        # no per-iter renorm: values decay to zero but MXU timing is
        # magnitude-independent, and any elementwise op would tax the
        # measurement with extra HBM passes
        def body(c, _):
            return c @ w, ()
        return jax.lax.scan(body, x, None, length=iters)[0]

    r = chain(x, w)
    float(jnp.sum(r[:1, :1].astype(jnp.float32)))
    best = float("inf")
    for _ in range(3):  # a ceiling: keep the best window (run-to-run ~10%)
        t0 = time.perf_counter()
        r = chain(x, w)
        float(jnp.sum(r[:1, :1].astype(jnp.float32)))
        best = min(best, time.perf_counter() - t0)
    best = max(best - _RTT_S, 1e-6)  # remove the per-sync tunnel latency
    return 2 * n * n * n * iters / best / 1e12


def _measure_conv_peak():
    """Measured bf16 conv ceiling (TF/s) over the ResNet-50 residual-stage
    3x3 shapes (56²x64, 28²x128, 14²x256, 7²x512 — equal FLOPs per stage by
    design), each a pure same-channel conv chain with NO elementwise
    traffic, so the number is an upper bound the train step's effective
    TF/s can be read against (it cannot sit below a well-formed model's
    achieved rate the way a single narrow-channel probe did)."""
    import time

    import jax
    import jax.numpy as jnp
    from jax import lax

    # iters large enough that device time dwarfs RTT jitter (the subtraction
    # is a constant, but RTT itself wanders ~±15 ms between syncs)
    B, iters = 128, 60
    rng = np.random.RandomState(0)
    total_flops = 0.0
    total_dt = 0.0
    for H, C in ((56, 64), (28, 128), (14, 256), (7, 512)):
        x = jnp.asarray(rng.randn(B, C, H, H) * 0.1, jnp.bfloat16)
        w = jnp.asarray(rng.randn(C, C, 3, 3) * 0.1, jnp.bfloat16)
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))

        @jax.jit
        def chain(x, w, dn=dn):
            def body(c, _):
                return lax.conv_general_dilated(
                    c, w, (1, 1), "SAME", dimension_numbers=dn), ()
            return jax.lax.scan(body, x, None, length=iters)[0]

        r = chain(x, w)
        float(jnp.sum(r[:1, :1, :1, :1].astype(jnp.float32)))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            r = chain(x, w)
            float(jnp.sum(r[:1, :1, :1, :1].astype(jnp.float32)))
            best = min(best, time.perf_counter() - t0)
        total_flops += 2 * B * H * H * C * C * 9 * iters
        total_dt += max(best - _RTT_S, 1e-6)  # remove per-sync tunnel latency
    return total_flops / total_dt / 1e12


def _measure_hbm_bw():
    """Measured streaming HBM bandwidth (GB/s): a deep in-jit chain of
    fused elementwise passes over a 512 MB buffer (each pass = one read +
    one write).  The denominator for the decode roofline
    (llama_decode_stream_gb_per_tok / this = the floor ms/token)."""
    import time

    import jax
    import jax.numpy as jnp

    n = 256 * 1024 * 1024  # 512 MB of bf16
    iters = 30
    x = jnp.ones((n,), jnp.bfloat16)

    @jax.jit
    def chain(x):
        def body(c, _):
            # NB: the multiplier must NOT round to 1.0 in bf16 (1.0000001
            # does!) or XLA folds the whole loop to identity
            return c * jnp.bfloat16(1.0078125), ()
        return jax.lax.scan(body, x, None, length=iters)[0]

    r = chain(x)
    float(jnp.sum(r[:2].astype(jnp.float32)))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        r = chain(x)
        float(jnp.sum(r[:2].astype(jnp.float32)))
        best = min(best, time.perf_counter() - t0)
    best = max(best - _RTT_S, 1e-6)
    return 2 * 2 * n * iters / best / 1e9  # read+write per pass


def _bench_llama(on_accel):
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_accel:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=12, num_attention_heads=16, num_key_value_heads=16,
            max_position_embeddings=2048, dtype="bfloat16",
            tensor_parallel=False, use_flash_attention=True,
        )
        batch, seq, steps, warmup = 8, 2048, 10, 3
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False, use_flash_attention=False)
        batch, seq, steps, warmup = 2, 128, 2, 1

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_accel:
        model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=3e-4, weight_decay=0.01,
                                 parameters=model.parameters())

    def loss_fn(ids, labels):
        logits = model(ids)
        # no f32 cast: cross_entropy's fused hard-label path does the
        # softmax math in f32 WITHOUT materializing f32 [N, 32000] logits
        # (2.1 GB/pass at this shape)
        return paddle.nn.functional.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]),
            labels.reshape([-1]),
        )

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    ids = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (batch, seq), np.int32))
    labels = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (batch, seq), np.int32))

    for _ in range(warmup):
        loss = step(ids, labels)
    float(loss.item())
    # median of three measurement windows: robust to remote-link hiccups
    # without silently reporting a lucky fastest window
    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, labels)
        float(loss.item())
        windows.append(time.perf_counter() - t0)
    # median window minus the ONE host sync's tunnel latency it contains
    dt = max(sorted(windows)[1] - _RTT_S, 1e-6)

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    tokens = batch * seq
    # model flops per train step: 6*N per token (fwd 2N + bwd 4N)
    # + causal attention matmuls: fwd 2*2*B*S^2*h per layer (QK^T, AV; causal => /2), x3 train
    attn_flops = 3 * 2 * batch * seq * seq * cfg.hidden_size * cfg.num_hidden_layers
    flops_per_step = 6 * n_params * tokens + attn_flops
    tps = tokens * steps / dt
    mfu = (flops_per_step * steps / dt) / V5E_PEAK_FLOPS
    return {"llama_tokens_per_sec_per_chip": round(tps, 1),
            "llama_mfu": round(mfu, 4),
            "llama_n_params": n_params,
            "llama_step_ms": round(1000 * dt / steps, 1)}


def _bench_decode(on_accel):
    """Autoregressive decode throughput: compiled static-cache generate()
    (prefill + lax.scan over steps in ONE program)."""
    import time

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_accel:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=12, num_attention_heads=16, num_key_value_heads=16,
            max_position_embeddings=2048, dtype="bfloat16",
            tensor_parallel=False, use_flash_attention=True,  # flash prefill
        )
        batch, prompt_len, new_tokens = 8, 1024, 128
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False, use_flash_attention=False)
        batch, prompt_len, new_tokens = 2, 16, 8

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_accel:
        model.bfloat16()
    model.eval()
    ids = paddle.to_tensor(
        np.random.randint(0, cfg.vocab_size, (batch, prompt_len), np.int32))

    def timed(ntok):
        out = model.generate(ids, max_new_tokens=ntok)  # compile
        _ = np.asarray(out._value)
        best = float("inf")
        for _ in range(3):  # tunnel RTT wanders ~±15 ms; best-of-3 steadies it
            t0 = time.perf_counter()
            out = model.generate(ids, max_new_tokens=ntok)
            _ = np.asarray(out._value)
            best = min(best, time.perf_counter() - t0)
        return max(best - _RTT_S, 1e-6)

    dt = timed(new_tokens)
    res = {"llama_decode_tokens_per_sec": round(batch * new_tokens / dt, 1),
           "llama_decode_batch": batch, "llama_decode_prompt_len": prompt_len}
    if on_accel:
        # steady-state ms/token (prefill subtracted), read against the
        # weight+kv-streaming roofline at the chip's MEASURED stream rate
        dt_half = timed(new_tokens // 2)
        per_tok = (dt - dt_half) / (new_tokens - new_tokens // 2)
        if per_tok > 1e-6:  # RTT subtraction can floor tiny windows
            res["llama_decode_ms_per_token"] = round(per_tok * 1000, 2)
            res["llama_decode_steady_tokens_per_sec"] = round(batch / per_tok, 1)
        # throughput scaling: weights amortize over a bigger decode batch
        ids32 = paddle.to_tensor(
            np.random.randint(0, cfg.vocab_size, (32, prompt_len), np.int32))

        def timed32(ntok):
            out = model.generate(ids32, max_new_tokens=ntok)
            _ = np.asarray(out._value)
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                out = model.generate(ids32, max_new_tokens=ntok)
                _ = np.asarray(out._value)
                best = min(best, time.perf_counter() - t0)
            return max(best - _RTT_S, 1e-6)

        d32 = timed32(new_tokens)
        d32_half = timed32(new_tokens // 2)
        per32 = (d32 - d32_half) / (new_tokens - new_tokens // 2)
        if per32 > 1e-6:
            res["llama_decode_b32_steady_tokens_per_sec"] = round(32 / per32, 1)
        n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
        kv_bytes = (2 * cfg.num_hidden_layers * batch
                    * (prompt_len + new_tokens)
                    * cfg.num_key_value_heads
                    * (cfg.hidden_size // cfg.num_attention_heads) * 2)
        res["llama_decode_stream_gb_per_tok"] = round(
            (2 * n_params + kv_bytes) / 1e9, 3)
    return res


def _bench_llama7b_layer(on_accel):
    """One LLaMA-2-7B-dimension decoder layer (h=4096, ffn=11008, 32 heads)
    fwd+bwd at seq 2048 — anchors per-layer ms for BASELINE config #5 (the
    7B tp+pp+sharding run a single chip cannot hold; 32 layers x this
    number ~= the per-chip compute slice).  Ref: BASELINE.md:30."""
    if not on_accel:
        return {}
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig
    from paddle_tpu.models.llama import LlamaDecoderLayer, _rope_cache
    from paddle_tpu.tensor.tensor import Tensor

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_hidden_layers=1, num_attention_heads=32, num_key_value_heads=32,
        max_position_embeddings=2048, dtype="bfloat16",
        tensor_parallel=False, use_flash_attention=True)
    paddle.seed(0)
    layer = LlamaDecoderLayer(cfg)
    layer.bfloat16()
    params, buffers = layer.functional_state()
    cos, sin = _rope_cache(128, 2048, cfg.rope_theta)
    B, S = 1, 2048

    def fwd_loss(params, x):
        from paddle_tpu.autograd import tape as _tape

        restore = layer.bind_functional_state(params, buffers)
        try:
            with _tape.no_grad():  # whole-function AD, the TrainStep pattern
                out = layer(Tensor(x), (Tensor(cos), Tensor(sin)))
        finally:
            restore()
        return jnp.sum(out._value.astype(jnp.float32) ** 2)

    step = jax.jit(jax.grad(fwd_loss, argnums=1))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, S, 4096) * 0.02, jnp.bfloat16)
    g = step(params, x)
    float(jnp.sum(g[:1, :1, :1].astype(jnp.float32)))
    iters = 20
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(iters):
            g = step(params, g)  # chain to keep the device busy
        float(jnp.sum(g[:1, :1, :1].astype(jnp.float32)))
        best = min(best, time.perf_counter() - t0)
    dt = max(best - _RTT_S, 1e-6) / iters
    n_params = sum(int(np.prod(p.shape)) for p in layer.parameters())
    # fwd 2N + bwd 4N per token + attention 3*(2*2*B*S^2*h)/2 causal
    flops = 6 * n_params * B * S + 3 * 2 * B * S * S * 4096
    return {"llama7b_layer_ms": round(dt * 1000, 2),
            "llama7b_layer_tfs": round(flops / dt / 1e12, 1)}


def _bench_resnet(on_accel):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet50

    batch = 128 if on_accel else 8
    img = 224 if on_accel else 64
    steps = 20 if on_accel else 2
    warmup = 5 if on_accel else 1

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    if on_accel:
        model.bfloat16()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    ce = nn.CrossEntropyLoss()

    def loss_fn(x, y):
        logits = model(x)
        return ce(logits.astype("float32"), y)

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    x = paddle.to_tensor(np.random.rand(batch, 3, img, img).astype(np.float32) * 2 - 1,
                         dtype="bfloat16" if on_accel else "float32")
    y = paddle.to_tensor(np.random.randint(0, 1000, (batch,), np.int32))

    for _ in range(warmup):
        loss = step(x, y)
    float(loss.item())
    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(x, y)
        float(loss.item())
        windows.append(time.perf_counter() - t0)
    dt = max(sorted(windows)[1] - _RTT_S, 1e-6)

    ips = batch * steps / dt
    # ResNet-50 fwd ~= 4.1 GFLOP/img at 224^2 (2*MACs); train ~= 3x fwd
    mfu = (ips * 3 * 4.1e9) / V5E_PEAK_FLOPS
    return {"resnet50_images_per_sec": round(ips, 2), "resnet50_mfu": round(mfu, 4)}


def main():
    import jax

    on_accel = jax.default_backend() not in ("cpu",)
    out = {}
    if on_accel:
        # measure the chip's gemm ceiling FIRST, on a clean HBM — after the
        # model benches the number is polluted by allocator state
        try:
            global _RTT_S
            _RTT_S = _measure_rtt()
            out["hw_rtt_ms_measured"] = round(_RTT_S * 1000, 1)
            out["hw_gemm_tfs_measured"] = round(_measure_gemm_peak(), 1)
            out["hw_conv_tfs_measured"] = round(_measure_conv_peak(), 1)
            out["hw_hbm_gbs_measured"] = round(_measure_hbm_bw(), 0)
        except Exception as e:
            out["hw_peak_error"] = repr(e)[:200]
    try:
        out.update(_bench_llama(on_accel))
    except Exception as e:  # keep the line printable even if one bench dies
        out["llama_error"] = repr(e)[:300]
    try:
        out.update(_bench_resnet(on_accel))
    except Exception as e:
        out["resnet_error"] = repr(e)[:300]
    try:
        out.update(_bench_decode(on_accel))
    except Exception as e:
        out["decode_error"] = repr(e)[:300]
    try:
        out.update(_bench_llama7b_layer(on_accel))
    except Exception as e:
        out["llama7b_layer_error"] = repr(e)[:300]

    if on_accel and out.get("hw_gemm_tfs_measured") and out.get("llama_mfu"):
        out["llama_mfu_vs_measured_peak"] = round(
            out["llama_mfu"] * (V5E_PEAK_FLOPS / 1e12) / out["hw_gemm_tfs_measured"], 4)

    mfu = out.get("llama_mfu", 0.0)
    print(json.dumps({
        "metric": "llama_pretrain_mfu" if on_accel else "llama_pretrain_mfu_cpu_smoke",
        "value": mfu,
        "unit": "model_flops_utilization",
        "vs_baseline": round(mfu / 0.70, 4),
        "timing": "median_of_3_windows",
        **out,
    }))


if __name__ == "__main__":
    main()
