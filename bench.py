"""Headline benchmark: ResNet-50 training throughput (BASELINE config #2).

Runs the compiled TrainStep (forward+backward+SGD-momentum in one XLA program) in
bfloat16 on whatever accelerator is attached (the driver provides one TPU v5e chip)
and prints ONE JSON line.

vs_baseline: the reference repo publishes no numbers (BASELINE.md), so the comparison
oracle is the public Paddle-CUDA ResNet-50 AMP number on V100 (~780 images/s, from
Paddle's own model-benchmark CI era); vs_baseline = images_per_sec / 780.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet50

    on_accel = jax.default_backend() not in ("cpu",)
    batch = 128 if on_accel else 8
    img = 224 if on_accel else 64
    steps = 20 if on_accel else 3
    warmup = 5 if on_accel else 1

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    model.bfloat16() if on_accel else None
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    ce = nn.CrossEntropyLoss()

    def loss_fn(x, y):
        logits = model(x)
        return ce(logits.astype("float32"), y)

    step = paddle.jit.TrainStep(model, loss_fn, opt)

    dtype = np.float32
    x = paddle.to_tensor(np.random.rand(batch, 3, img, img).astype(dtype) * 2 - 1,
                         dtype="bfloat16" if on_accel else "float32")
    y = paddle.to_tensor(np.random.randint(0, 1000, (batch,), np.int32))

    for _ in range(warmup):
        loss = step(x, y)
    float(loss.item())  # sync

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    float(loss.item())  # sync
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec" if on_accel else "resnet50_train_images_per_sec_cpu_smoke",
        "value": round(ips, 2),
        "unit": "images/s",
        "vs_baseline": round(ips / 780.0, 4),
    }))


if __name__ == "__main__":
    main()
