"""Headline benchmarks (BASELINE.md north stars).

1. LLaMA decoder pretrain step — tokens/sec/chip + MFU (BASELINE config #5 /
   ERNIE north star: >=70% MFU target on v5e, peak 197 TFLOP/s bf16).
2. ResNet-50 training throughput — images/s + MFU (BASELINE config #2).

Runs the compiled TrainStep (forward+backward+optimizer in one XLA program) in
bfloat16 on whatever accelerator is attached (the driver provides one TPU v5e chip)
and prints ONE JSON line.  The primary metric is the transformer MFU; ResNet numbers
ride along as extra fields.

vs_baseline: MFU / 0.70 (the BASELINE.md target); >1.0 beats the target.
"""
from __future__ import annotations

import json
import time

import numpy as np

V5E_PEAK_FLOPS = 197e12  # bf16, one v5e chip (nominal)

_RTT_S = 0.0  # measured dispatch+sync round-trip of the attached chip


def paged_capacity_trace(L_pad, page_size=128):
    """Deterministic mixed-length serving trace for the paged-kv capacity
    accounting (shared with tools/project_pod.py so the 'derived' PROJECTION
    numbers can never drift from what bench.py measures): context lengths
    100..L_pad in steps of 100 — deliberately OFF the page grid so the
    round-up-to-page waste is represented.  Returns (trace, mean pages per
    request at `page_size`)."""
    trace = list(range(100, int(L_pad) + 1, 100))
    pages_mean = sum(-(-t // page_size) for t in trace) / len(trace)
    return trace, pages_mean


def shared_prefix_trace(L_pad, page_size=128, n_requests=32):
    """Deterministic fleet-style SHARED-PREFIX serving trace (shared with
    tools/project_pod.py so the 'derived' PROJECTION numbers can never
    drift from what bench.py measures): every request carries one common
    system prompt plus a small varied tail.  The shared length is
    deliberately OFF the page grid so the tail page is partially filled —
    later requests fork it copy-on-write, the behavior the prefix cache
    must pay for.  Returns the trace geometry plus the analytic per-request
    page accounting: admission charges only the UNIQUE pages (tail + the
    COW fork), so effective capacity multiplies by
    total_pages / unique_pages as the fleet share amortizes."""
    ps = int(page_size)
    # the shared prompt spans N full pages PLUS ps/8 tokens into the next
    # page, and the varied tail + decode stay inside that same page — so
    # the divergence point always sits inside a partially-filled shared
    # page; N is clamped so the whole trace fits inside L_pad
    tail_len = max(1, ps // 16)
    new_tokens = max(1, ps // 8)
    extra = max(1, ps // 8) + tail_len + new_tokens
    if int(L_pad) - extra < ps:
        raise ValueError(
            f"shared_prefix_trace needs L_pad >= page_size + {extra} to fit "
            f"one full shared page plus the divergent tail; got "
            f"L_pad={L_pad}, page_size={ps}")
    shared_full_pages = max(1, min((3 * int(L_pad)) // 4 // ps,
                                   (int(L_pad) - extra) // ps))
    shared_len = shared_full_pages * ps + max(1, ps // 8)
    total_tokens = shared_len + tail_len + new_tokens
    total_pages = -(-total_tokens // ps)
    unique_pages = total_pages - shared_full_pages
    # every request but the first serves its shared tokens from the cache
    hit_ratio = (n_requests - 1) / n_requests \
        * shared_len / (shared_len + tail_len)
    return {"n_requests": n_requests, "shared_len": shared_len,
            "tail_len": tail_len, "new_tokens": new_tokens,
            "total_pages": total_pages,
            "shared_full_pages": shared_full_pages,
            "unique_pages": unique_pages,
            "hit_ratio": round(hit_ratio, 4)}


def _measure_rtt():
    """The tunneled chip pays ~100ms dispatch+sync latency PER HOST SYNC —
    every single-sync timing window is inflated by this constant.  Measure
    it once (tiny jit call) and subtract it from every window below;
    otherwise small probes read as latency, not compute (the r2 conv
    'ceiling' of 7.5 TF/s was exactly this artifact)."""
    import time

    import jax
    import jax.numpy as jnp

    x = jnp.ones((8, 8), jnp.float32)

    @jax.jit
    def f(x):
        return x + 1.0

    _ = np.asarray(f(x))
    samples = []
    for _i in range(5):
        t0 = time.perf_counter()
        _ = np.asarray(f(x))
        samples.append(time.perf_counter() - t0)
    return sorted(samples)[len(samples) // 2]


def _measure_gemm_peak():
    """Measured bf16 gemm ceiling of the attached chip (TF/s): a 30-deep
    in-jit chain of [8192,8192]x[8192,8192] matmuls.  Context for the MFU
    number — tunneled/throttled chips deliver well below nominal peak
    (observed ~128 TF/s vs the 197 spec), so mfu_vs_measured shows how close
    the compiled step is to what this hardware can actually do."""
    import time

    import jax
    import jax.numpy as jnp

    n, iters = 8192, 30
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, n) * 0.01, jnp.bfloat16)
    w = jnp.asarray(rng.randn(n, n) * 0.01, jnp.bfloat16)

    @jax.jit
    def chain(x, w):
        # no per-iter renorm: values decay to zero but MXU timing is
        # magnitude-independent, and any elementwise op would tax the
        # measurement with extra HBM passes
        def body(c, _):
            return c @ w, ()
        return jax.lax.scan(body, x, None, length=iters)[0]

    r = chain(x, w)
    float(jnp.sum(r[:1, :1].astype(jnp.float32)))
    ws = []
    for _ in range(5):
        t0 = time.perf_counter()
        r = chain(x, w)
        float(jnp.sum(r[:1, :1].astype(jnp.float32)))
        ws.append(time.perf_counter() - t0)
    # median window: a best-of window can catch an RTT dip below the median
    # RTT being subtracted and read ABOVE the chip's nominal peak
    dt = max(sorted(ws)[len(ws) // 2] - _RTT_S, 1e-6)
    return 2 * n * n * n * iters / dt / 1e12


def _measure_conv_peak():
    """Measured bf16 conv ceiling (TF/s) over the ResNet-50 residual-stage
    3x3 shapes (56²x64, 28²x128, 14²x256, 7²x512 — equal FLOPs per stage by
    design), each a pure same-channel conv chain with NO elementwise
    traffic, so the number is an upper bound the train step's effective
    TF/s can be read against (it cannot sit below a well-formed model's
    achieved rate the way a single narrow-channel probe did)."""
    import time

    import jax
    import jax.numpy as jnp
    from jax import lax

    # iters sized so each WINDOW is ~100+ ms: the tunnel RTT wanders ±15 ms
    # between syncs, so short windows minus the median RTT read garbage in
    # both directions (r3 reported 88 TF/s, an intermediate run 244 — above
    # nominal peak — from the same probe at 60 iters); median window, not
    # best, since this is a denominator for the ResNet MFU story
    B, iters = 128, 600
    rng = np.random.RandomState(0)
    total_flops = 0.0
    total_dt = 0.0
    for H, C in ((56, 64), (28, 128), (14, 256), (7, 512)):
        x = jnp.asarray(rng.randn(B, C, H, H) * 0.1, jnp.bfloat16)
        w = jnp.asarray(rng.randn(C, C, 3, 3) * 0.1, jnp.bfloat16)
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))

        @jax.jit
        def chain(x, w, dn=dn):
            def body(c, _):
                return lax.conv_general_dilated(
                    c, w, (1, 1), "SAME", dimension_numbers=dn), ()
            return jax.lax.scan(body, x, None, length=iters)[0]

        r = chain(x, w)
        float(jnp.sum(r[:1, :1, :1, :1].astype(jnp.float32)))
        ws = []
        for _ in range(3):
            t0 = time.perf_counter()
            r = chain(x, w)
            float(jnp.sum(r[:1, :1, :1, :1].astype(jnp.float32)))
            ws.append(time.perf_counter() - t0)
        total_flops += 2 * B * H * H * C * C * 9 * iters
        total_dt += max(sorted(ws)[1] - _RTT_S, 1e-6)
    return total_flops / total_dt / 1e12


def _measure_hbm_bw():
    """Measured streaming READ bandwidth (GB/s) — the decode denominator
    (decode streams weights+kv and writes almost nothing).

    Probe design notes (each clause closes a measured failure mode):
    - per-pass `sum(|x + c|, axis=1)` with a carried c: not algebraically
      factorable, so XLA can neither hoist the reduction out of the loop
      (sum(x)+n*c) nor push it into the operand (reduce-max probes both
      collapsed to tiny loops and read >1 TB/s);
    - 200 chained passes over 512 MB = a ~150 ms window: the tunnel RTT
      wanders +-15 ms between syncs, so short windows minus the measured
      median RTT produce garbage in BOTH directions (r3's 448 GB/s "ceiling"
      sat BELOW the decode step's own achieved rate);
    - median-of-5 windows, not best: this number is a denominator, so an
      optimistic outlier would overstate every roofline fraction built on it."""
    import time

    import jax
    import jax.numpy as jnp

    R, C, iters = 16384, 16384, 200  # 512 MB bf16, ~77 GB read per window
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(R, C) * 0.1, jnp.bfloat16)

    @jax.jit
    def chain(x):
        def body(c, _):
            m = jnp.sum(jnp.abs(x + c[:, None]), axis=1, dtype=jnp.float32)
            return (m * jnp.float32(1e-6)).astype(jnp.bfloat16), ()
        return jax.lax.scan(body, jnp.zeros((R,), jnp.bfloat16), None,
                            length=iters)[0]

    r = chain(x)
    float(jnp.sum(r[:2].astype(jnp.float32)))
    windows = []
    for _ in range(5):
        t0 = time.perf_counter()
        r = chain(x)
        float(jnp.sum(r[:2].astype(jnp.float32)))
        windows.append(time.perf_counter() - t0)
    dt = max(sorted(windows)[2] - _RTT_S, 1e-6)
    return 2 * R * C * iters / dt / 1e9


def _bench_llama(on_accel):
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_accel:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=12, num_attention_heads=16, num_key_value_heads=16,
            max_position_embeddings=2048, dtype="bfloat16",
            tensor_parallel=False, use_flash_attention=True,
        )
        batch, seq, steps, warmup = 8, 2048, 10, 3
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False, use_flash_attention=False)
        batch, seq, steps, warmup = 2, 128, 2, 1

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_accel:
        model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=3e-4, weight_decay=0.01,
                                 parameters=model.parameters())

    def loss_fn(ids, labels):
        logits = model(ids)
        # no f32 cast: cross_entropy's fused hard-label path does the
        # softmax math in f32 WITHOUT materializing f32 [N, 32000] logits
        # (2.1 GB/pass at this shape)
        return paddle.nn.functional.cross_entropy(
            logits.reshape([-1, cfg.vocab_size]),
            labels.reshape([-1]),
        )

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    ids = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (batch, seq), np.int32))
    labels = paddle.to_tensor(np.random.randint(0, cfg.vocab_size, (batch, seq), np.int32))

    for _ in range(warmup):
        loss = step(ids, labels)
    float(loss.item())
    # median of three measurement windows: robust to remote-link hiccups
    # without silently reporting a lucky fastest window
    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, labels)
        float(loss.item())
        windows.append(time.perf_counter() - t0)
    # median window minus the ONE host sync's tunnel latency it contains
    dt = max(sorted(windows)[1] - _RTT_S, 1e-6)

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    tokens = batch * seq
    # model flops per train step: 6*N per token (fwd 2N + bwd 4N)
    # + causal attention matmuls: fwd 2*2*B*S^2*h per layer (QK^T, AV; causal => /2), x3 train
    attn_flops = 3 * 2 * batch * seq * seq * cfg.hidden_size * cfg.num_hidden_layers
    flops_per_step = 6 * n_params * tokens + attn_flops
    tps = tokens * steps / dt
    mfu = (flops_per_step * steps / dt) / V5E_PEAK_FLOPS
    return {"llama_tokens_per_sec_per_chip": round(tps, 1),
            "llama_mfu": round(mfu, 4),
            "llama_n_params": n_params,
            "llama_step_ms": round(1000 * dt / steps, 1)}


def _bench_decode(on_accel):
    """Autoregressive decode throughput: compiled static-cache generate()
    (prefill + lax.scan over steps in ONE program)."""
    import time

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_accel:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=12, num_attention_heads=16, num_key_value_heads=16,
            max_position_embeddings=2048, dtype="bfloat16",
            tensor_parallel=False, use_flash_attention=True,  # flash prefill
        )
        batch, prompt_len, new_tokens = 8, 1024, 128
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False, use_flash_attention=False)
        batch, prompt_len, new_tokens = 2, 16, 8

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_accel:
        model.bfloat16()
    model.eval()
    ids = paddle.to_tensor(
        np.random.randint(0, cfg.vocab_size, (batch, prompt_len), np.int32))

    def timed(the_ids, ntok, cache_dtype=None, kv_layout=None, reps=3):
        out = model.generate(the_ids, max_new_tokens=ntok,
                             cache_dtype=cache_dtype,
                             kv_layout=kv_layout)  # compile
        _ = np.asarray(out._value)
        ws = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = model.generate(the_ids, max_new_tokens=ntok,
                                 cache_dtype=cache_dtype,
                                 kv_layout=kv_layout)
            _ = np.asarray(out._value)
            ws.append(time.perf_counter() - t0)
        # median window: steady-state deltas difference out the RTT anyway,
        # and a best-of window would overstate the achieved rate
        return max(sorted(ws)[len(ws) // 2] - _RTT_S, 1e-6)

    def steady(the_ids, ntok, cache_dtype=None, kv_layout=None):
        d_full = timed(the_ids, ntok, cache_dtype, kv_layout)
        d_half = timed(the_ids, ntok // 2, cache_dtype, kv_layout)
        return d_full, (d_full - d_half) / (ntok - ntok // 2)

    dt, per_tok = steady(ids, new_tokens) if on_accel else (
        timed(ids, new_tokens), 0.0)
    res = {"llama_decode_tokens_per_sec": round(batch * new_tokens / dt, 1),
           "llama_decode_batch": batch, "llama_decode_prompt_len": prompt_len}
    if on_accel:
        n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
        # the static cache pads L to a multiple of 128 for the Pallas decode
        # kernel; the step streams the PADDED buffers (generation.py L_pad)
        L_pad = ((prompt_len + new_tokens + 127) // 128) * 128
        hd = cfg.hidden_size // cfg.num_attention_heads
        # kv_elems counts BOTH k and v rows (the leading factor 2), so the
        # per-row cost below is payload + ONE f32 scale
        kv_elems = 2 * cfg.num_hidden_layers * batch * L_pad \
            * cfg.num_key_value_heads
        kv_bytes_bf16 = kv_elems * hd * 2
        kv_bytes_int8 = kv_elems * (hd * 1 + 4)  # int8 payload + f32 scale
        # streamed params exclude the INPUT embedding table: decode gathers B
        # rows of it, it never streams (the r4 floor counted it and the round-5
        # kernel then beat that "floor" — the accounting was the error)
        streamed = n_params - cfg.vocab_size * cfg.hidden_size
        res["llama_decode_stream_gb_per_tok"] = round(
            (2 * streamed + kv_bytes_bf16) / 1e9, 3)
        if per_tok > 1e-6:
            res["llama_decode_ms_per_token"] = round(per_tok * 1000, 2)
            res["llama_decode_steady_tokens_per_sec"] = round(batch / per_tok, 1)
        # int8 cache: the Pallas decode kernel dequantizes in VMEM, so the
        # int8 stream is genuinely half — capacity AND bandwidth lever
        _, per_q8 = steady(ids, new_tokens, "int8")
        if per_q8 > 1e-6:
            res["llama_decode_int8_ms_per_token"] = round(per_q8 * 1000, 2)
            res["llama_decode_int8_steady_tokens_per_sec"] = round(
                batch / per_q8, 1)
        res["llama_decode_int8_stream_gb_per_tok"] = round(
            (2 * streamed + kv_bytes_int8) / 1e9, 3)
        # int8 capacity win: max decode batch at this context before the kv
        # cache exhausts HBM (measured device limit when the runtime reports
        # one), bf16 vs int8 — the judge-requested kv_int8_max_batch_gain
        try:
            import jax as _jax

            stats = _jax.devices()[0].memory_stats() or {}
            hbm = float(stats.get("bytes_limit", 16e9))
        except Exception:
            hbm = 16e9
        budget = hbm * 0.9 - 2 * n_params  # 10% runtime/activation slack
        per_batch_bf16 = kv_bytes_bf16 / batch
        per_batch_int8 = kv_bytes_int8 / batch
        res["kv_int8_max_batch_gain"] = round(
            (budget / per_batch_int8) / max(budget / per_batch_bf16, 1e-9), 2)
        res["kv_bf16_max_batch"] = int(budget / per_batch_bf16)
        res["kv_int8_max_batch"] = int(budget / per_batch_int8)
        # throughput scaling: weights amortize over a bigger decode batch
        ids32 = paddle.to_tensor(
            np.random.randint(0, cfg.vocab_size, (32, prompt_len), np.int32))
        _, per32 = steady(ids32, new_tokens)
        if per32 > 1e-6:
            res["llama_decode_b32_steady_tokens_per_sec"] = round(32 / per32, 1)
        # int8 at the capacity-bound batch: its halved kv stream must win here
        _, per32q = steady(ids32, new_tokens, "int8")
        if per32q > 1e-6:
            res["llama_decode_int8_b32_steady_tokens_per_sec"] = round(
                32 / per32q, 1)
        # PAGED decode (ragged paged attention kernel behind page tables):
        # same math, page-pool residency — the serving engine's layout
        _, per_pg = steady(ids, new_tokens, kv_layout="paged")
        if per_pg > 1e-6:
            res["llama_decode_paged_ms_per_token"] = round(per_pg * 1000, 2)
            res["llama_decode_paged_steady_tokens_per_sec"] = round(
                batch / per_pg, 1)
        _, per_pg8 = steady(ids, new_tokens, "int8", kv_layout="paged")
        if per_pg8 > 1e-6:
            res["llama_decode_paged_int8_steady_tokens_per_sec"] = round(
                batch / per_pg8, 1)
        _, per_pg32 = steady(ids32, new_tokens, kv_layout="paged")
        if per_pg32 > 1e-6:
            res["llama_decode_paged_b32_steady_tokens_per_sec"] = round(
                32 / per_pg32, 1)
        # paged CAPACITY: a dense server reserves L_pad rows per slot (the
        # longest admissible context); pages follow ACTUAL lengths.  Model
        # the deterministic mixed-length trace from paged_capacity_trace
        # (contexts 100..L_pad step 100, page_size 128) and report the max
        # decode batch the same HBM budget holds (the dense counterpart of
        # this accounting is kv_bf16_max_batch above, whose every slot costs
        # the full L_pad rows)
        ps_pg = 128
        trace, pages_mean = paged_capacity_trace(L_pad, ps_pg)
        rows_mean = pages_mean * ps_pg
        row_bytes_bf16 = 2 * cfg.num_hidden_layers \
            * cfg.num_key_value_heads * hd * 2
        row_bytes_int8 = 2 * cfg.num_hidden_layers \
            * cfg.num_key_value_heads * (hd + 4)
        res["kv_paged_max_batch"] = int(budget / (rows_mean * row_bytes_bf16))
        res["kv_paged_int8_max_batch"] = int(
            budget / (rows_mean * row_bytes_int8))
        res["kv_paged_max_batch_gain"] = round(
            res["kv_paged_max_batch"] / max(res["kv_bf16_max_batch"], 1), 2)
        # fraction of allocated page rows holding real tokens on this trace
        res["kv_paged_pool_utilization"] = round(
            sum(trace) / (len(trace) * rows_mean), 3)
        # PREFIX-CACHE capacity: the shared-prefix fleet trace (one system
        # prompt + varied tails).  Admission charges only UNIQUE pages, so
        # the same budget holds (budget_pages - shared) / unique_per_req
        # concurrent requests — vs budget_pages / total_pages unshared
        tr = shared_prefix_trace(L_pad, ps_pg)
        page_bytes_bf16 = ps_pg * row_bytes_bf16
        page_bytes_int8 = ps_pg * row_bytes_int8
        for tag, pb in (("", page_bytes_bf16), ("int8_", page_bytes_int8)):
            budget_pages = budget / pb
            res[f"kv_prefix_{tag}max_batch"] = int(
                (budget_pages - tr["shared_full_pages"])
                // tr["unique_pages"])
        res["kv_prefix_max_batch_gain"] = round(
            res["kv_prefix_max_batch"] / max(res["kv_paged_max_batch"], 1),
            2)
        res["kv_prefix_trace_hit_ratio"] = tr["hit_ratio"]
        res["kv_prefix_trace"] = {k: tr[k] for k in
                                  ("shared_len", "tail_len", "new_tokens",
                                   "total_pages", "unique_pages")}
    return res


def _bench_prefix_cache(on_accel):
    """Shared-prefix serving trace through the REAL engine (prefix cache
    on): measures the achieved llm_prefix_cache_hit_ratio, COW forks and
    prefix evictions on the deterministic trace shared_prefix_trace
    describes — the measured side of the kv_prefix_max_batch accounting
    above.  Runs a scaled-down trace on CPU so the number exists (tiny) in
    every round."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_accel:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=12, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=2048,
            dtype="bfloat16", tensor_parallel=False,
            use_flash_attention=True)
        L, ps, slots, n_req, new_toks = 1152, 128, 8, 16, 16
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False,
                               use_flash_attention=False)
        L, ps, slots, n_req, new_toks = 128, 32, 2, 6, 4

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_accel:
        model.bfloat16()
    model.eval()
    tr = shared_prefix_trace(L, ps, n_requests=n_req)
    rng = np.random.RandomState(0)
    shared = rng.randint(0, cfg.vocab_size, tr["shared_len"]).astype(np.int32)
    prompts = [np.concatenate([shared,
                               rng.randint(0, cfg.vocab_size, tr["tail_len"])
                               .astype(np.int32)]) for _ in range(n_req)]
    eng = LLMEngine(model, max_batch_slots=slots, max_seq_len=L,
                    kv_layout="paged", page_size=ps,
                    num_pages=slots * (tr["total_pages"] + 1),
                    prefill_chunk=ps)
    eng.warmup()
    t0 = time.perf_counter()
    futs = [eng.submit(p, max_new_tokens=new_toks) for p in prompts]
    eng.run_until_complete()
    dt = max(time.perf_counter() - t0, 1e-6)
    for f in futs:
        f.result(timeout=1)
    # engine-local counts, not the process-global registry's
    st = eng.stats()["prefix_cache"]
    return {"llm_prefix_cache_hit_ratio": round(st["hit_ratio"], 4),
            "prefix_trace_requests": n_req,
            "prefix_cow_copies": int(st["cow_copies"]),
            "prefix_evictions": int(st["evictions"]),
            "prefix_trace_tokens_per_sec": round(
                n_req * new_toks / dt, 1)}


def _bench_kv_tiers(on_accel):
    """Hierarchical kv tiers through the REAL engine: the effective prefix
    capacity multiplier over HBM-only, the promote-vs-reprefill cost per
    page (the economics that justify the copy), and the off-tick-path
    guard number ``kv_promote_us_per_page`` — the per-page promotion
    latency the CI sentinel watches so a regression that drags the upload
    toward re-prefill cost fails loudly instead of silently burning the
    capacity win."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_accel:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=12, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=2048,
            dtype="bfloat16", tensor_parallel=False,
            use_flash_attention=True)
        L, ps, slots, host_pages, plen, new_toks = 1152, 128, 8, 64, 640, 8
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False,
                               use_flash_attention=False)
        L, ps, slots, host_pages, plen, new_toks = 128, 32, 2, 16, 96, 2

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_accel:
        model.bfloat16()
    model.eval()
    rng = np.random.RandomState(0)
    num_pages = slots * (plen // ps + 2)
    eng = LLMEngine(model, max_batch_slots=slots, max_seq_len=L,
                    kv_layout="paged", page_size=ps, num_pages=num_pages,
                    prefill_chunk=ps, host_cache_pages=host_pages)
    eng.warmup()
    # per-call promotion timing, engine-local (the registry histogram
    # aggregates across every engine the process ever ran)
    promote = {"s": 0.0, "pages": 0}
    inner = eng._promote_from_tiers

    def timed(req):
        t = time.perf_counter()
        n = inner(req)
        promote["s"] += time.perf_counter() - t
        promote["pages"] += n
        return n

    eng._promote_from_tiers = timed
    # warm the gather/upload programs on a same-shape cycle (same pow-2
    # upload bucket): first use compiles, and a compile is not the number
    warm = rng.randint(0, cfg.vocab_size, plen).astype(np.int32)
    eng.generate(warm, max_new_tokens=1)
    while eng.demote_step(force=True):
        pass
    eng._evict_prefix(int(eng._page_cached.sum()))
    eng.generate(warm, max_new_tokens=1)
    promote["s"], promote["pages"] = 0.0, 0
    tiers0 = eng.stats()["prefix_cache"]["tiers"]
    prompt = rng.randint(0, cfg.vocab_size, plen).astype(np.int32)
    t0 = time.perf_counter()
    eng.generate(prompt, max_new_tokens=new_toks)   # cold chunked prefill
    t_cold = time.perf_counter() - t0
    while eng.demote_step(force=True):              # stage every page ...
        pass
    eng._evict_prefix(int(eng._page_cached.sum()))  # ... and drop the HBM copy
    t0 = time.perf_counter()
    eng.generate(prompt, max_new_tokens=new_toks)   # promote path
    t_promote = time.perf_counter() - t0
    fresh = rng.randint(0, cfg.vocab_size, plen).astype(np.int32)
    t0 = time.perf_counter()
    eng.generate(fresh, max_new_tokens=new_toks)    # warm re-prefill baseline
    t_reprefill = time.perf_counter() - t0
    pages = max(promote["pages"], 1)
    tiers = eng.stats()["prefix_cache"]["tiers"]
    # capacity: pages a warm prefix can live in without being destroyed —
    # HBM page pool (minus the trash page) alone vs with the lower tiers
    hbm_pages = num_pages - 1
    return {
        "kv_tier_capacity_multiplier": round(
            (hbm_pages + host_pages) / hbm_pages, 2),
        "kv_tier_host_pages": host_pages,
        "kv_tier_hbm_pages": hbm_pages,
        "kv_promote_us_per_page": round(1e6 * promote["s"] / pages, 1),
        "kv_promote_vs_reprefill_ratio": round(
            t_promote / max(t_reprefill, 1e-9), 3),
        "kv_tier_promoted_pages": int(tiers["promotions"]
                                      - tiers0["promotions"]),
        "kv_tier_demoted_pages": int(tiers["demotions"]
                                     - tiers0["demotions"]),
        "kv_tier_hit_tokens": int(tiers["host"]["hit_tokens"]
                                  + tiers["disk"]["hit_tokens"]
                                  - tiers0["host"]["hit_tokens"]
                                  - tiers0["disk"]["hit_tokens"]),
        "kv_tier_cold_prefill_ms": round(t_cold * 1e3, 1),
        "kv_tier_promote_path_ms": round(t_promote * 1e3, 1),
    }


def _bench_spec_decode(on_accel):
    """Speculative decoding through the REAL engine: steady decode tok/s
    spec-on vs spec-off on the same deterministic trace, plus the
    acceptance/rollback accounting behind the speedup.

    The drafter is a REPLAY drafter (each request's precomputed solo
    greedy continuation) — deterministic and model-independent, so the
    number isolates the verify-path mechanics (K+1 tokens per compiled
    call, rollback trims) at a controlled acceptance rate rather than
    mixing in a particular corpus's n-gram hit rate.  The engine-reported
    acceptance_ratio and rollback counters are emitted alongside so a
    regression in EITHER the mechanism or the accounting moves a number."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    if on_accel:
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5504,
            num_hidden_layers=12, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=2048,
            dtype="bfloat16", tensor_parallel=False,
            use_flash_attention=True)
        slots, L, ps, plen, new_toks, K = 8, 1024, 128, 256, 64, 4
    else:
        cfg = LlamaConfig.tiny(tensor_parallel=False,
                               use_flash_attention=False)
        slots, L, ps, plen, new_toks, K = 2, 128, 32, 16, 8, 3

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_accel:
        model.bfloat16()
    model.eval()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, plen).astype(np.int32)
               for _ in range(slots)]
    ids = paddle.to_tensor(np.stack(prompts))
    solo = np.asarray(model.generate(ids, max_new_tokens=new_toks)._value)
    seqs = [np.concatenate([p, solo[i]]) for i, p in enumerate(prompts)]

    class _Replay:
        name = "replay"

        def propose(self, context, k):
            ctx = np.asarray(context, np.int32).reshape(-1)
            out = np.zeros(int(k), np.int32)
            for s in seqs:
                if ctx.size <= s.size and (s[:plen] == ctx[:plen]).all():
                    tail = s[ctx.size:ctx.size + int(k)]
                    out[:tail.size] = tail
                    break
            return out

    def run(spec_k, drafter=None):
        eng = LLMEngine(model, max_batch_slots=slots, max_seq_len=L,
                        kv_layout="paged", page_size=ps, prefill_chunk=ps,
                        spec_k=spec_k, spec_draft=drafter)
        eng.warmup()
        t0 = time.perf_counter()
        futs = [eng.submit(p, max_new_tokens=new_toks) for p in prompts]
        eng.run_until_complete()
        dt = max(time.perf_counter() - t0, 1e-6)
        for f in futs:
            f.result(timeout=1)  # parity itself is the test suite's job
        return slots * new_toks / dt, eng.stats()["spec"]

    off_tps, _ = run(0)
    on_tps, spec = run(K, _Replay())
    return {
        "spec_decode_tokens_per_sec": round(on_tps, 1),
        "spec_off_tokens_per_sec": round(off_tps, 1),
        "spec_decode_speedup": round(on_tps / max(off_tps, 1e-6), 2),
        "spec_decode_batch": slots,
        "spec_k": K,
        "spec_acceptance_ratio": round(spec["acceptance_ratio"], 4),
        "spec_verify_calls": int(spec["verify_calls"]),
        "spec_rolled_back_tokens": int(spec["rolled_back_tokens"]),
        "spec_rolled_back_pages": int(spec["rolled_back_pages"]),
    }


def _bench_ragged_attention(on_accel):
    """ONE ragged paged-attention kernel vs the gathered dense fallback,
    µs per call, at the two serving shapes that used to be dense-only: a
    prefill chunk (S = chunk) and the spec-verify ladder (S = K+1).  The
    A/B pins the SAME shapes through both paths via the dispatcher's
    _FORCE_PATH hook, so the delta is purely Pallas-kernel-walking-pages
    vs gather-every-page-then-masked-dense.  On CPU the kernel side runs
    in interpret mode — the numbers there are a smoke signal, not perf."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import decode_attention as da

    if on_accel:
        B, H, Hkv, D, ps, M = 8, 16, 8, 128, 128, 16  # 2k-token pool/slot
        shapes = (("prefill_chunk", 256, 1024), ("verify", 5, 1536))
        reps = 20
    else:
        B, H, Hkv, D, ps, M = 2, 4, 2, 128, 128, 4
        shapes = (("prefill_chunk", 128, 256), ("verify", 5, 200))
        reps = 2

    rng = np.random.RandomState(0)
    P = 1 + B * M  # page 0 is the trash page
    kp = jnp.asarray(rng.randn(P, Hkv, ps, D).astype(np.float32) * 0.3)
    vp = jnp.asarray(rng.randn(P, Hkv, ps, D).astype(np.float32) * 0.3)
    pt = jnp.asarray(
        [[1 + b * M + j for j in range(M)] for b in range(B)], jnp.int32)

    out = {"ragged_attn_batch": B, "ragged_attn_pages_per_slot": M}
    for tag, S, off in shapes:
        q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32) * 0.3)
        offs = jnp.full((B,), off, jnp.int32)

        def run(force):
            da._FORCE_PATH = force
            try:
                f = jax.jit(lambda qq: da.paged_decode_attention(
                    qq, kp, vp, offs, pt))
                _ = np.asarray(f(q))  # compile
                t0 = time.perf_counter()
                for _i in range(reps):
                    r = f(q)
                _ = np.asarray(r)
                return (time.perf_counter() - t0) / reps * 1e6
            finally:
                da._FORCE_PATH = None

        kern_us, dense_us = run(None), run("dense")
        out[f"ragged_attn_{tag}_kernel_us"] = round(kern_us, 1)
        out[f"ragged_attn_{tag}_dense_us"] = round(dense_us, 1)
        out[f"ragged_attn_{tag}_speedup"] = round(
            dense_us / max(kern_us, 1e-9), 2)
    return out


def _bench_llama7b_layer(on_accel):
    """One LLaMA-2-7B-dimension decoder layer (h=4096, ffn=11008, 32 heads)
    fwd+bwd at seq 2048 — anchors per-layer ms for BASELINE config #5 (the
    7B tp+pp+sharding run a single chip cannot hold; 32 layers x this
    number ~= the per-chip compute slice).  Ref: BASELINE.md:30."""
    if not on_accel:
        return {}
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig
    from paddle_tpu.models.llama import LlamaDecoderLayer, _rope_cache
    from paddle_tpu.tensor.tensor import Tensor

    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_hidden_layers=1, num_attention_heads=32, num_key_value_heads=32,
        max_position_embeddings=2048, dtype="bfloat16",
        tensor_parallel=False, use_flash_attention=True)
    paddle.seed(0)
    layer = LlamaDecoderLayer(cfg)
    layer.bfloat16()
    params, buffers = layer.functional_state()
    cos, sin = _rope_cache(128, 2048, cfg.rope_theta)
    B, S = 1, 2048

    def fwd_loss(params, x):
        from paddle_tpu.autograd import tape as _tape

        restore = layer.bind_functional_state(params, buffers)
        try:
            with _tape.no_grad():  # whole-function AD, the TrainStep pattern
                out = layer(Tensor(x), (Tensor(cos), Tensor(sin)))
        finally:
            restore()
        return jnp.sum(out._value.astype(jnp.float32) ** 2)

    # grad wrt params AND x: the full 6N train backward (dW matmuls
    # included — r3 differentiated x only, overstating the layer TF/s)
    step = jax.jit(jax.grad(fwd_loss, argnums=(0, 1)))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, S, 4096) * 0.02, jnp.bfloat16)
    _, g = step(params, x)
    float(jnp.sum(g[:1, :1, :1].astype(jnp.float32)))
    iters = 20
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(iters):
            _, g = step(params, g)  # chain to keep the device busy
        float(jnp.sum(g[:1, :1, :1].astype(jnp.float32)))
        best = min(best, time.perf_counter() - t0)
    dt = max(best - _RTT_S, 1e-6) / iters
    n_params = sum(int(np.prod(p.shape)) for p in layer.parameters())
    # fwd 2N + bwd 4N per token + attention 3*(2*2*B*S^2*h)/2 causal
    flops = 6 * n_params * B * S + 3 * 2 * B * S * S * 4096
    return {"llama7b_layer_ms": round(dt * 1000, 2),
            "llama7b_layer_tfs": round(flops / dt / 1e12, 1)}


def _bench_llama_h4096(on_accel):
    """LLaMA pretrain MFU at the 7B shape (h=4096, ffn=11008, seq 2048) —
    as many layers as one chip's HBM holds with AdamW state.  The 738M
    h=2048 headline config is small-dim-limited; this is the MFU number at
    BASELINE config #5's actual hidden sizes (BASELINE.md:30)."""
    if not on_accel:
        return {}
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    for layers, batch in ((5, 4), (4, 4), (4, 2)):
        try:
            cfg = LlamaConfig(
                vocab_size=32000, hidden_size=4096, intermediate_size=11008,
                num_hidden_layers=layers, num_attention_heads=32,
                num_key_value_heads=32, max_position_embeddings=2048,
                dtype="bfloat16", tensor_parallel=False,
                use_flash_attention=True)
            paddle.seed(0)
            model = LlamaForCausalLM(cfg)
            model.bfloat16()
            opt = paddle.optimizer.AdamW(learning_rate=3e-4, weight_decay=0.01,
                                         parameters=model.parameters())

            def loss_fn(ids, labels):
                logits = model(ids)
                return paddle.nn.functional.cross_entropy(
                    logits.reshape([-1, cfg.vocab_size]),
                    labels.reshape([-1]))

            step = paddle.jit.TrainStep(model, loss_fn, opt)
            seq, steps = 2048, 6
            ids = paddle.to_tensor(
                np.random.randint(0, cfg.vocab_size, (batch, seq), np.int32))
            labels = paddle.to_tensor(
                np.random.randint(0, cfg.vocab_size, (batch, seq), np.int32))
            for _ in range(2):
                loss = step(ids, labels)
            float(loss.item())
            windows = []
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(steps):
                    loss = step(ids, labels)
                float(loss.item())
                windows.append(time.perf_counter() - t0)
            dt = max(sorted(windows)[1] - _RTT_S, 1e-6)
            n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
            tokens = batch * seq
            attn_flops = 3 * 2 * batch * seq * seq * cfg.hidden_size * layers
            flops_per_step = 6 * n_params * tokens + attn_flops
            mfu = (flops_per_step * steps / dt) / V5E_PEAK_FLOPS
            return {"llama_h4096_mfu": round(mfu, 4),
                    "llama_h4096_layers": layers,
                    "llama_h4096_tokens_per_sec": round(tokens * steps / dt, 1),
                    "llama_h4096_n_params": n_params}
        except Exception as e:
            last = repr(e)[:200]
    return {"llama_h4096_error": last}


def _bench_ernie(on_accel):
    """ERNIE/BERT-base MLM+NSP pretrain — THE driver north-star metric
    (BASELINE.md:22: 'ERNIE-3.0 tokens/sec/chip').

    Runs the REFERENCE pretrain recipe: masked_lm_positions with
    max_predictions_per_seq = 20 (create_pretraining_data's 15% of seq 128),
    MLM head over the gathered masked rows only.  FLOPs are accounted
    HONESTLY for that recipe — encoder matmuls on all B*S tokens, MLM
    transform+decoder on the B*20 masked rows, bidirectional attention term —
    NOT the dense 6*N*T upper bound (which would overstate MFU ~1.19x for
    work the masked head never does).  See ERNIE_BREAKDOWN.md for the
    ablation ladder (694 -> ~420 ms/step) and the h=768 gemm-shape ceiling
    audit this number sits against."""
    if not on_accel:
        return {}
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertConfig, ErnieForPretraining

    cfg = BertConfig.base()
    paddle.seed(0)
    model = ErnieForPretraining(cfg)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                                 parameters=model.parameters())
    batch, seq, n_pred, steps = 512, 128, 20, 8

    def loss_fn(ids, seg, pos, labels, nsp):
        loss, _ = model(ids, token_type_ids=seg, masked_lm_labels=labels,
                        next_sentence_label=nsp, masked_positions=pos)
        return loss

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    seg = paddle.to_tensor((rng.rand(batch, seq) > 0.5).astype(np.int32))
    pos = paddle.to_tensor(np.stack(
        [rng.choice(seq, n_pred, replace=False) for _ in range(batch)]).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (batch, n_pred)).astype(np.int32))
    nsp = paddle.to_tensor(rng.randint(0, 2, (batch, 1)).astype(np.int32))
    for _ in range(2):
        loss = step(ids, seg, pos, labels, nsp)
    float(loss.item())
    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, seg, pos, labels, nsp)
        float(loss.item())
        windows.append(time.perf_counter() - t0)
    dt = max(sorted(windows)[1] - _RTT_S, 1e-6)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    tokens = batch * seq
    rows_masked = batch * n_pred
    h, L = cfg.hidden_size, cfg.num_hidden_layers
    # matmul param counts (weights only; gathers/biases excluded)
    enc_matmul = L * (h * 3 * h + h * h + 2 * h * cfg.intermediate_size)
    head_matmul = h * h + h * cfg.vocab_size        # transform + tied decoder
    pooled_matmul = h * h + h * 2                   # pooler + NSP head
    attn_flops = 3 * 4 * batch * seq * seq * h * L  # bidirectional (no causal /2)
    flops_per_step = (6 * enc_matmul * tokens + 6 * head_matmul * rows_masked
                      + 6 * pooled_matmul * batch + attn_flops)
    return {"ernie_tokens_per_sec_per_chip": round(tokens * steps / dt, 1),
            "ernie_mfu": round((flops_per_step * steps / dt) / V5E_PEAK_FLOPS, 4),
            "ernie_n_params": n_params,
            "ernie_batch_seq": [batch, seq],
            "ernie_masked_per_seq": n_pred,
            "ernie_step_ms": round(dt / steps * 1e3, 1),
            "ernie_flops_per_step": flops_per_step}


def _bench_vit(on_accel):
    """ViT-base/16 ImageNet training throughput (BASELINE config #2)."""
    if not on_accel:
        return {}
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import vit_b_16

    paddle.seed(0)
    model = vit_b_16(num_classes=1000)
    model.bfloat16()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, weight_decay=0.05,
                                 parameters=model.parameters())
    ce = paddle.nn.CrossEntropyLoss()
    batch, steps = 128, 10

    def loss_fn(x, y):
        return ce(model(x).astype("float32"), y)

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    x = paddle.to_tensor(
        np.random.rand(batch, 3, 224, 224).astype(np.float32) * 2 - 1,
        dtype="bfloat16")
    y = paddle.to_tensor(np.random.randint(0, 1000, (batch,), np.int32))
    for _ in range(3):
        loss = step(x, y)
    float(loss.item())
    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(x, y)
        float(loss.item())
        windows.append(time.perf_counter() - t0)
    dt = max(sorted(windows)[1] - _RTT_S, 1e-6)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    toks = 197  # 14x14 patches + cls
    attn_flops = 3 * 4 * batch * toks * toks * 768 * 12
    flops_per_step = 6 * n_params * batch * toks + attn_flops
    ips = batch * steps / dt
    return {"vit_images_per_sec": round(ips, 1),
            "vit_mfu": round((flops_per_step * steps / dt) / V5E_PEAK_FLOPS, 4)}


def _bench_ocr(on_accel):
    """PP-OCR-style det+rec pipeline (BASELINE config #3): DBNet detection on
    640x640 pages + CRNN recognition of the cropped text lines (4 crops per
    page at the standard 32x320 rec shape), end-to-end inference."""
    if not on_accel:
        return {}
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.vision import ocr

    paddle.seed(0)
    det = ocr.DBNet(backbone_scale=0.5, arch="small", neck_channels=96)
    det.bfloat16()
    det.eval()
    rec = ocr.CRNN(num_classes=6625, hidden_size=48)
    rec.bfloat16()
    rec.eval()
    B, crops_per_page = 8, 4
    rng = np.random.RandomState(0)
    pages = paddle.to_tensor(rng.rand(B, 3, 640, 640).astype(np.float32),
                             dtype="bfloat16")
    lines = paddle.to_tensor(
        rng.rand(B * crops_per_page, 3, 32, 320).astype(np.float32),
        dtype="bfloat16")

    from paddle_tpu.autograd import tape as _tape

    def run(pg, ln):
        with _tape.no_grad():
            maps = det(paddle.Tensor(pg))  # DBHead returns {"maps": ...}
            logits = rec(paddle.Tensor(ln))
        m = maps["maps"] if isinstance(maps, dict) else maps
        return m._value, logits._value

    import jax.numpy as jnp

    def _sync(m):
        # fetch a device-side SCALAR: np.asarray(m) would pull the full
        # [8, 3, 640, 640] maps (~20 MB) through the tunnel per window
        float(jnp.sum(m.reshape(-1)[:2].astype(jnp.float32)))

    jrun = jax.jit(run)
    m, lg = jrun(pages._value, lines._value)
    _sync(m); _sync(lg)
    steps = 40  # window >> the ±15ms RTT jitter (see _measure_hbm_bw notes)
    windows = []
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(steps):
            m, lg = jrun(pages._value, lines._value)
        _sync(m)
        windows.append(time.perf_counter() - t0)
    dt = max(sorted(windows)[2] - _RTT_S, 1e-6)
    return {"ocr_e2e_images_per_sec": round(B * steps / dt, 1),
            "ocr_det_batch": B, "ocr_rec_lines_per_page": crops_per_page}


def _bench_resnet(on_accel):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.vision.models import resnet50

    batch = 128 if on_accel else 8
    img = 224 if on_accel else 64
    steps = 20 if on_accel else 2
    warmup = 5 if on_accel else 1

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    if on_accel:
        model.bfloat16()
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())
    ce = nn.CrossEntropyLoss()

    def loss_fn(x, y):
        logits = model(x)
        return ce(logits.astype("float32"), y)

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    x = paddle.to_tensor(np.random.rand(batch, 3, img, img).astype(np.float32) * 2 - 1,
                         dtype="bfloat16" if on_accel else "float32")
    y = paddle.to_tensor(np.random.randint(0, 1000, (batch,), np.int32))

    for _ in range(warmup):
        loss = step(x, y)
    float(loss.item())
    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(x, y)
        float(loss.item())
        windows.append(time.perf_counter() - t0)
    dt = max(sorted(windows)[1] - _RTT_S, 1e-6)

    ips = batch * steps / dt
    # ResNet-50 fwd ~= 4.1 GFLOP/img at 224^2 (2*MACs); train ~= 3x fwd
    mfu = (ips * 3 * 4.1e9) / V5E_PEAK_FLOPS
    return {"resnet50_images_per_sec": round(ips, 2), "resnet50_mfu": round(mfu, 4)}


def _bench_observability(on_accel):
    """Telemetry overhead guard (ISSUE 5): per-step wall-time delta of the
    instrumented train step vs `observability.disable()` on the SAME
    compiled program — future BENCH rounds catch a telemetry regression as
    obs_overhead_us_per_step drifting up.  Runs on CPU too (the
    instrumentation cost is host-side by construction)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import observability as obs

    batch, hidden = (256, 1024) if on_accel else (32, 64)
    steps = 60 if on_accel else 30

    paddle.seed(0)
    model = nn.Linear(hidden, hidden)
    opt = paddle.optimizer.SGD(learning_rate=0.01,
                               parameters=model.parameters())

    def loss_fn(x, y):
        return paddle.nn.functional.mse_loss(model(x), y)

    step = paddle.jit.TrainStep(model, loss_fn, opt)
    x = paddle.to_tensor(
        np.random.rand(batch, hidden).astype(np.float32))
    y = paddle.to_tensor(
        np.random.rand(batch, hidden).astype(np.float32))

    def window():
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(x, y)
        float(loss.item())
        return (time.perf_counter() - t0) / steps

    out = {}
    try:
        step(x, y)  # compile outside both windows
        # median of 3 per mode, interleaved so allocator/thermal drift
        # lands on both sides
        on_s, off_s = [], []
        for _ in range(3):
            obs.enable()
            on_s.append(window())
            obs.disable()
            off_s.append(window())
        on_med, off_med = sorted(on_s)[1], sorted(off_s)[1]
        out["obs_overhead_us_per_step"] = round((on_med - off_med) * 1e6, 2)
        out["obs_overhead_frac"] = round(
            (on_med - off_med) / off_med, 5) if off_med > 0 else 0.0
        out["obs_disabled_us_per_step"] = round(off_med * 1e6, 2)
    finally:
        obs.enable()
    return out


def _bench_goodput(on_accel):
    """Goodput-ledger overhead guard (ISSUE 20): cost of one
    section+carve+token step on an enabled vs disabled ledger.  The
    ledger sits inside the engine tick and the recovery step loop, so
    its enabled cost must stay in single-digit microseconds and its
    disabled cost at ~one dict lookup — a regression here taxes every
    step of every instrumented run.  Host-side by construction: runs on
    CPU too."""
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import goodput

    iters = 20000 if on_accel else 5000

    def window(led):
        t0 = time.perf_counter()
        for _ in range(iters):
            with led.section("step"):
                led.carve("compile", 1e-9)
            led.count_tokens("useful", 1)
        return (time.perf_counter() - t0) / iters

    out = {}
    try:
        # median of 3 per mode, interleaved so drift lands on both sides
        on_s, off_s = [], []
        for _ in range(3):
            obs.enable()
            on_s.append(window(goodput.TimeLedger("train")))
            obs.disable()
            off_s.append(window(goodput.TimeLedger("train")))
        on_med, off_med = sorted(on_s)[1], sorted(off_s)[1]
        out["goodput_overhead_us_per_step"] = round(on_med * 1e6, 3)
        out["goodput_disabled_us_per_step"] = round(off_med * 1e6, 3)
    finally:
        obs.enable()
    return out


def _bench_xplane_parse(on_accel):
    """Profiling-plane cost guard (ISSUE 14): wire-parse + per-op
    aggregation throughput of the dependency-free XPlane reader over a
    realistic blob (the committed golden dump replicated 64x —
    concatenated XSpace serializations merge, so the blob is one legal
    multi-plane dump).  trace_report --xplane runs at operator cadence,
    but a regression from linear to quadratic (span copies, repeated
    metadata resolution) would make real multi-GB TPU dumps unusable.
    Host-side by construction: runs on CPU too."""
    import os

    from paddle_tpu.observability import xplane

    golden = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "data", "golden.xplane.pb")
    with open(golden, "rb") as f:
        blob = f.read() * 64

    def med(fn, n):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    parse_s = med(lambda: xplane.parse_xspace(blob), 9)
    space = xplane.parse_xspace(blob)
    summ_s = med(lambda: xplane.per_op_summary(space), 9)
    mb = len(blob) / 1e6
    return {
        "xplane_parse_us_per_mb": round(parse_s * 1e6 / mb, 1),
        "xplane_summary_us_per_mb": round(summ_s * 1e6 / mb, 1),
        "xplane_bench_ops": len(xplane.per_op_summary(space)),
    }


def _bench_roofline(on_accel):
    """Roofline-plane cost guard (ISSUE 17): residual-join throughput —
    µs per MB of dump to go from a parsed XSpace + census to the sorted
    residual table (predict + match + rank).  Companion to
    xplane_summary_us_per_mb: the sentinel runs at CI cadence over real
    multi-GB dumps, so the join must stay linear in ops.  Host-side by
    construction: runs on CPU too."""
    import os

    from paddle_tpu.observability import roofline, xplane

    golden = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tests", "data", "golden.xplane.pb")
    with open(golden, "rb") as f:
        blob = f.read() * 64
    measured = xplane.per_op_summary(xplane.parse_xspace(blob))
    # synthetic census covering every measured op (worst-case: every row
    # matches, nothing early-outs) plus prefixed variants to exercise the
    # containment fallback
    census = {}
    for i, name in enumerate(measured):
        census[name.rsplit("/", 1)[-1]] = {
            "opcode": "dot", "flops": 1e9 * (i + 1), "bytes": 1e6 * (i + 1)}

    def med(fn, n):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    join_s = med(lambda: roofline.residual_rows(measured, census,
                                                197e12, 819e9), 9)
    mb = len(blob) / 1e6
    return {
        "roofline_join_us_per_mb": round(join_s * 1e6 / mb, 1),
        "roofline_bench_ops": len(measured),
    }


def _profile_roofline(on_accel, round_name=None):
    """bench --profile: the measured-vs-predicted loop (ISSUE 17).

    Two deliberately opposite configs — a gemm scan chain that should pin
    the compute roof and a streaming reduce that should pin the memory
    roof — each compiled once (the same executable feeds
    census.per_op_census AND the profiled window), wrapped in a
    ProfilingSession, joined into per-config residual reports, merged
    into ONE content-addressed round, and (with --round) persisted as
    ROOFLINE_<round>.json for the sentinel to diff against.  Residual
    tables go to stderr (stdout stays the one-JSON-line contract)."""
    import os
    import sys

    import jax
    import jax.numpy as jnp

    from paddle_tpu import cost_model
    from paddle_tpu.distributed import census as _census
    from paddle_tpu.observability import profiling, roofline

    pf = cost_model.peak_flops_per_device()
    pbw = cost_model.peak_hbm_bytes_per_sec()
    if pbw <= 0:  # unknown host (CPU): explicit measured fallback
        pbw = cost_model.peak_hbm_bytes_per_sec(measure=True)
    if pf <= 0:
        # small-scale gemm probe (the 8192^2 hw probe is accelerator
        # budget): enough to anchor CPU rounds, spec table rules on TPU
        n = 1024
        x = jnp.ones((n, n), jnp.float32)

        @jax.jit
        def chain(x):
            def body(c, _):
                return c @ x, ()
            return jax.lax.scan(body, x, None, length=8)[0]

        jax.block_until_ready(chain(x))
        t0 = time.perf_counter()
        jax.block_until_ready(chain(x))
        dt = time.perf_counter() - t0
        pf = 8 * 2 * n ** 3 / dt if dt > 0 else 0.0

    d = 2048 if on_accel else 512
    m = 1 << (26 if on_accel else 22)  # streaming vector elements
    steps = 8
    dtype = jnp.bfloat16 if on_accel else jnp.float32

    def gemm_chain(x, w):
        # unrolled on purpose: a lax.scan hides the dots inside the
        # while-body computation, which the entry-only census can't cost
        for _ in range(4):
            x = x @ w
        return x

    def stream_reduce(a, b):
        return jnp.sum(jnp.abs(a + b), dtype=jnp.float32)

    configs = {
        "gemm": (gemm_chain, (jnp.ones((d, d), dtype) * 0.01,
                              jnp.ones((d, d), dtype) * 0.01),
                 {"kind": "gemm_scan_chain", "d": d, "depth": 4,
                  "dtype": str(jnp.dtype(dtype)), "steps": steps}),
        "stream": (stream_reduce, (jnp.ones((m,), dtype),
                                   jnp.ones((m,), dtype)),
                   {"kind": "stream_abs_sum", "elems": m,
                    "dtype": str(jnp.dtype(dtype)), "steps": steps}),
    }

    out = {}
    reports = {}
    for name, (fn, args, cfg) in configs.items():
        compiled = jax.jit(fn).lower(*args).compile()
        cens = _census.per_op_census(compiled)
        r = compiled(*args)
        jax.block_until_ready(r)  # warm before the profiled window
        with profiling.ProfilingSession() as prof:
            for _ in range(steps):
                r = compiled(*args)
            jax.block_until_ready(r)
        rep = roofline.build_report(prof.summary, cens, pf, pbw,
                                    config=cfg)
        reports[name] = rep
        s = rep["summary"]
        print(f"--- roofline[{name}] ---", file=sys.stderr)
        print(roofline.render_text(rep, top=10), file=sys.stderr)
        out[f"roofline_{name}_residual_ratio"] = s["residual_ratio"]
        out[f"roofline_{name}_wasted_us"] = s["wasted_us"]
        out[f"roofline_{name}_ops"] = s["ops"]
    merged = roofline.merge_reports(reports)
    roofline.export_gauges(merged)
    out["roofline_round_key"] = merged["key"]
    out["roofline_peak_flops_per_sec"] = round(pf, 1)
    out["roofline_peak_hbm_bytes_per_sec"] = round(pbw, 1)
    if round_name:
        root = os.path.dirname(os.path.abspath(__file__))
        out["roofline_round_path"] = roofline.save_round(
            merged, root, round_name)
        print(f"persisted roofline round {round_name} "
              f"(key {merged['key']})", file=sys.stderr)
    return out


def _bench_alerting(on_accel):
    """Alerting-plane cost guard (ISSUE 7): exposition parse cost of a
    realistic scraped payload and rule-evaluation cost per engine tick
    over the default rule set — the companions to
    obs_overhead_us_per_step, so the sense/decide loop can't quietly grow
    into a hot-path tax.  Host-side by construction: runs on CPU too."""
    from paddle_tpu.observability import alerts, metrics, scrape, slo

    # a realistic fleet payload: the full instrumented registry (the
    # process importing bench has llm/train/store series registered) plus
    # synthetic per-replica series to hit fleet-scale label cardinality
    reg = metrics.REGISTRY
    for i in range(8):
        slo.track(f"bench_alert_series_{i}", 0.01 * (i + 1))
    synth = metrics.MetricRegistry()
    g = synth.gauge("bench_fleet_depth", "synthetic", labelnames=("rank",))
    h = synth.histogram("bench_fleet_seconds", "synthetic",
                        labelnames=("rank",))
    for rank in range(16):
        g.labels(rank=str(rank)).set(rank * 3.0)
        for k in range(8):
            h.labels(rank=str(rank)).observe(0.001 * (k + 1))
    payload = reg.render_prometheus() + synth.render_prometheus()

    def med(fn, n):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    parse_s = med(lambda: scrape.parse_prometheus(payload), 9)
    families = scrape.parse_prometheus(payload)
    samples = scrape.SampleSet().add_families(families, {"target": "t0"})

    rules = alerts.default_rules() + [
        alerts.Rule("bench_backlog", metric="bench_fleet_depth", op=">",
                    threshold=30.0, for_s=5.0),
        alerts.Rule("bench_rising", kind="delta",
                    metric="bench_fleet_seconds_count", op=">",
                    threshold=100.0, window_s=60.0),
    ]
    engine = alerts.AlertEngine(rules=rules, clock=lambda: 0.0)
    tick = {"t": 0.0}

    def one_tick():
        tick["t"] += 1.0
        engine.evaluate(samples, now=tick["t"])

    one_tick()  # first tick builds the instance cells
    eval_s = med(one_tick, 50)
    return {
        "alert_parse_us_per_scrape": round(parse_s * 1e6, 1),
        "alert_eval_us_per_tick": round(eval_s * 1e6, 1),
        "alert_scrape_samples": len(samples),
        "alert_rules_count": len(rules),
    }


def _bench_tracing(on_accel):
    """Request-tracing cost guard (ISSUE 8): per-request overhead of the
    full traced lifecycle (start -> queue_wait -> admission span -> 4
    prefill-chunk spans -> coalesced decode summary -> end + tail-sample
    offer) in three modes — enabled-and-kept, enabled-but-sampled-out,
    and observability disabled — next to obs_overhead_us_per_step, so the
    forensic plane can't quietly grow into a hot-path tax.  Host-side by
    construction: runs on CPU too."""
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import tracing

    n = 4000 if on_accel else 1500
    hist = obs.metrics.MetricRegistry().histogram(
        "bench_trace_seconds", "synthetic")

    def lifecycle(tracer):
        t = tracer.start_trace("llm_request", prompt_tokens=128,
                               max_new_tokens=32)
        t.add_span("queue_wait", duration_s=1e-4)
        adm = t.span("admission", slot=0, episode=1,
                     cached_tokens=64).open()
        for i in range(4):
            with t.span("llm_prefill_chunk", index=i, tokens=32):
                pass
        adm.close()
        t.add_span("decode", duration_s=1e-3, ticks=32, tokens=32)
        hist.observe(1e-3, exemplar=t.trace_id or None)
        t.end("ok", generated_tokens=32)

    def window(tracer, reps):
        t0 = time.perf_counter()
        for _ in range(reps):
            lifecycle(tracer)
        return (time.perf_counter() - t0) / reps

    def baseline_window(reps):
        # the same loop shape with NO tracer calls: what "tracing absent"
        # costs, the disabled mode's comparison floor
        t0 = time.perf_counter()
        for _ in range(reps):
            hist.observe(1e-3)
        return (time.perf_counter() - t0) / reps

    out = {}
    try:
        obs.enable()
        kept = tracing.Tracer(store=tracing.TraceStore(
            capacity=64, sample_every=1))
        sampled_out = tracing.Tracer(store=tracing.TraceStore(
            capacity=64, sample_every=0))  # healthy traces all dropped
        med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
        kept_s, samp_s, dis_s, base_s = [], [], [], []
        for _ in range(3):  # interleaved medians, like _bench_observability
            obs.enable()
            kept_s.append(window(kept, n))
            samp_s.append(window(sampled_out, n))
            base_s.append(baseline_window(n))
            obs.disable()
            dis_s.append(window(kept, n))
        out["trace_overhead_us_per_request_enabled"] = round(
            med(kept_s) * 1e6, 3)
        out["trace_overhead_us_per_request_sampled_out"] = round(
            med(samp_s) * 1e6, 3)
        out["trace_overhead_us_per_request_disabled"] = round(
            med(dis_s) * 1e6, 3)
        out["trace_overhead_us_per_request_baseline"] = round(
            med(base_s) * 1e6, 3)
    finally:
        obs.enable()
    return out


def _bench_router(on_accel):
    """Serving-plane guard (ISSUE 12): the SAME deterministic
    shared-prefix trace routed through 2 in-process replicas by the
    prefix-affinity router vs alternated round-robin — affinity must win
    on fleet-wide prefix-cache hit ratio — plus the router's own
    per-request overhead (placement decision + admission ack over the
    wire), so the front door can't quietly grow into a serving tax.
    Host-side by construction: runs on CPU too."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.inference.router import ReplicaServer, Router
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny(tensor_parallel=False,
                           use_flash_attention=False)
    ps, slots, n_req, new_toks = 16, 2, 8 if on_accel else 6, 4
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    head = rng.randint(0, cfg.vocab_size, 2 * ps).astype(np.int32)
    prompts = [np.concatenate([head,
                               rng.randint(0, cfg.vocab_size, ps // 2)
                               .astype(np.int32)]) for _ in range(n_req)]

    def engine():
        return LLMEngine(model, max_batch_slots=slots, max_seq_len=128,
                         kv_layout="paged", page_size=ps,
                         prefill_chunk=ps, metrics_port=0)

    def fleet_hit_ratio(engines):
        hit = sum(e.stats()["prefix_cache"]["hit_tokens"] for e in engines)
        tot = sum(e.stats()["prefix_cache"]["prompt_tokens"]
                  for e in engines)
        return hit / tot if tot else 0.0

    # affinity-routed pass: live wire path through 2 replicas
    reps = [ReplicaServer(engine(), name=f"bench-r{i}") for i in range(2)]
    for r in reps:
        r.engine.start()
    router = Router(reps, page_size=ps, affinity_blocks=4)
    try:
        t0 = time.perf_counter()
        for p in prompts:
            router.request(p, max_new_tokens=new_toks, timeout=120)
        dt = max(time.perf_counter() - t0, 1e-6)
        rz = router.routerz()
        aff_ratio = fleet_hit_ratio([r.engine for r in reps])
    finally:
        router.stop()
        for r in reps:
            r.engine.stop()

    # round-robin baseline: the SAME trace alternated across fresh engines
    rr = [engine(), engine()]
    try:
        futs = [rr[i % 2].submit(p, max_new_tokens=new_toks)
                for i, p in enumerate(prompts)]
        for e in rr:
            e.run_until_complete()
        for f in futs:
            f.result(timeout=1)
        rr_ratio = fleet_hit_ratio(rr)
    finally:
        for e in rr:
            e.stop()
    return {
        "router_affinity_hit_ratio": round(rz["affinity"]["hit_ratio"], 4),
        "router_prefix_cache_hit_ratio": round(aff_ratio, 4),
        "router_prefix_cache_hit_ratio_round_robin": round(rr_ratio, 4),
        "router_overhead_us_per_request": rz["overhead_us_mean"],
        "router_trace_requests": n_req,
        "router_trace_tokens_per_sec": round(n_req * new_toks / dt, 1),
    }


def _bench_tpulint(on_accel):
    """Static-analysis cost guard (ISSUE 18): tpulint file-rule throughput
    in microseconds per thousand source lines over the real package.  The
    pre-commit loop budget is "sub-second for a spot-lint"; a rule that
    re-walks the AST per node (quadratic) or re-parses per rule would blow
    that silently while --check still passes.  Runs the engine in-process
    (serial, file rules only — project rules import jax and are bounded by
    compile time, not lint time).  Host-only by construction."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        "_bench_tpulint_analysis",
        os.path.join(repo, "paddle_tpu", "analysis", "__init__.py"),
        submodule_search_locations=[
            os.path.join(repo, "paddle_tpu", "analysis")])
    analysis = importlib.util.module_from_spec(spec)
    import sys as _sys
    _sys.modules["_bench_tpulint_analysis"] = analysis
    spec.loader.exec_module(analysis)

    pairs = analysis.list_target_files(repo, ["paddle_tpu"])
    kloc = sum(sum(1 for _ in open(a, "rb")) for a, _ in pairs) / 1000.0

    def run():
        project = analysis.ProjectContext(repo)
        file_rules = [r for r in analysis.RULES.values()
                      if isinstance(r, analysis.FileRule)]
        n = 0
        for abspath, relpath in pairs:
            n += len(analysis.lint_file(project, abspath, relpath,
                                        file_rules))
        return n

    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        ts.append(time.perf_counter() - t0)
    med = sorted(ts)[len(ts) // 2]
    return {
        "tpulint_us_per_kloc": round(med * 1e6 / max(kloc, 1e-9), 1),
        "tpulint_bench_kloc": round(kloc, 1),
        "tpulint_bench_rules": len(analysis.RULES),
    }


def _bench_multi_tenant(on_accel):
    """Multi-tenant serving guard (ISSUE 15): the SAME deterministic trace
    decoded three ways — every request on its own adapter (the mixed
    many-tenant case the paged pool exists for), every request on ONE
    adapter, and a no-adapter base engine — so the batched-gather
    epilogue's cost and the adapter-MIX penalty (which must be ~zero:
    only the gather rows change) are both pinned.  Plus the host-side
    constraint-mask cost per decode tick (automaton mask + device
    upload), since that's the only per-tick work constrained decoding
    adds.  Host/gather-bound by construction: runs on CPU too."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.inference.constrain import compile_constraint
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.lora import (AdapterRegistry, LoraAdapter,
                                        lora_sites)

    cfg = LlamaConfig.tiny(tensor_parallel=False,
                           use_flash_attention=False)
    n_adapters = 64 if on_accel else 12
    slots, n_req, new_toks, ps = 4, (16 if on_accel else 8), 8, 16
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    sites = lora_sites(model)
    adapters = {f"a{i}": LoraAdapter.random(sites, rank=4, seed=1000 + i)
                for i in range(n_adapters)}
    reg = AdapterRegistry.from_adapters(model, adapters, rank=4)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, 12).astype(np.int32)
               for _ in range(n_req)]

    def run(eng, aids):
        futs = [eng.submit(p, max_new_tokens=new_toks, adapter_id=a)
                for p, a in zip(prompts, aids)]
        eng.run_until_complete()
        toks = sum(len(f.result(timeout=1)) for f in futs)
        return toks

    def timed(adapters_reg, aids):
        eng = LLMEngine(model, max_batch_slots=slots, max_seq_len=64,
                        kv_layout="paged", page_size=ps, prefill_chunk=ps,
                        adapters=adapters_reg)
        try:
            eng.warmup()
            run(eng, aids)  # prime the first-request eager-op compiles
            t0 = time.perf_counter()
            toks = run(eng, aids)
            return toks / max(time.perf_counter() - t0, 1e-6)
        finally:
            eng.stop()

    mixed_ids = [f"a{i % n_adapters}" for i in range(n_req)]
    mixed = timed(reg, mixed_ids)
    single = timed(reg, ["a0"] * n_req)
    base = timed(None, [None] * n_req)

    # host-side constraint cost per decode tick: advance-independent —
    # mask lookup for every slot + one [B, V] device upload, exactly what
    # the engine's constrained decode path does each tick
    tc = compile_constraint(r"[0-9]+", ["%d" % i if i < 10 else f"w{i}"
                                        for i in range(cfg.vocab_size)],
                            cfg.vocab_size - 1)
    cursors = [tc.cursor() for _ in range(slots)]
    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        mask = np.stack([c.mask() for c in cursors])
        jnp.asarray(mask).block_until_ready()
    mask_us = (time.perf_counter() - t0) / iters * 1e6

    return {
        "multi_tenant_adapters": n_adapters,
        "multi_tenant_mixed_tokens_per_sec": round(mixed, 1),
        "multi_tenant_single_adapter_tokens_per_sec": round(single, 1),
        "multi_tenant_base_tokens_per_sec": round(base, 1),
        "multi_tenant_mix_penalty_ratio": round(single / mixed, 3),
        "multi_tenant_lora_overhead_ratio": round(base / single, 3),
        "constraint_mask_us_per_tick": round(mask_us, 1),
    }


def main(argv=None):
    import argparse

    import jax

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--profile", action="store_true",
                    help="also run the roofline measured-vs-predicted "
                         "loop (_profile_roofline): per-config "
                         "ProfilingSession windows joined against their "
                         "census into residual tables (stderr) and "
                         "roofline_* fields on the JSON line")
    ap.add_argument("--round", default=None,
                    help="with --profile: persist the merged round as "
                         "ROOFLINE_<NAME>.json next to bench.py (the "
                         "sentinel baseline)")
    args = ap.parse_args(argv)

    on_accel = jax.default_backend() not in ("cpu",)
    out = {}
    if on_accel:
        # measure the chip's gemm ceiling FIRST, on a clean HBM — after the
        # model benches the number is polluted by allocator state
        try:
            global _RTT_S
            _RTT_S = _measure_rtt()
            out["hw_rtt_ms_measured"] = round(_RTT_S * 1000, 1)
            out["hw_gemm_tfs_measured"] = round(_measure_gemm_peak(), 1)
            out["hw_conv_tfs_measured"] = round(_measure_conv_peak(), 1)
            out["hw_hbm_gbs_measured"] = round(_measure_hbm_bw(), 0)
        except Exception as e:
            out["hw_peak_error"] = repr(e)[:200]
    # soft deadline: with ~13 jit compiles over the tunnel the full run is
    # ~30 min; if the harness kills us mid-bench the whole JSON line is
    # lost, so stop starting new benches near the budget and print
    deadline = time.monotonic() + float(
        __import__("os").environ.get("BENCH_BUDGET_S", "2700"))
    for fn, tag in ((_bench_llama, "llama"),
                    (_bench_llama_h4096, "llama_h4096"),
                    (_bench_resnet, "resnet"),
                    (_bench_decode, "decode"),
                    (_bench_prefix_cache, "prefix_cache"),
                    (_bench_kv_tiers, "kv_tiers"),
                    (_bench_spec_decode, "spec_decode"),
                    (_bench_ragged_attention, "ragged_attention"),
                    (_bench_llama7b_layer, "llama7b_layer"),
                    (_bench_ernie, "ernie"),
                    (_bench_vit, "vit"),
                    (_bench_ocr, "ocr"),
                    (_bench_observability, "observability"),
                    (_bench_goodput, "goodput"),
                    (_bench_alerting, "alerting"),
                    (_bench_tracing, "tracing"),
                    (_bench_xplane_parse, "xplane"),
                    (_bench_roofline, "roofline"),
                    (_bench_router, "router"),
                    (_bench_multi_tenant, "multi_tenant"),
                    (_bench_tpulint, "tpulint")):
        if time.monotonic() > deadline:
            out[f"{tag}_skipped"] = "bench budget exhausted"
            continue
        try:
            out.update(fn(on_accel))
        except Exception as e:  # keep the line printable even if one bench dies
            out[f"{tag}_error"] = repr(e)[:300]

    if args.profile:
        try:
            out.update(_profile_roofline(on_accel, round_name=args.round))
        except Exception as e:
            out["roofline_profile_error"] = repr(e)[:300]

    # headline MFU: the 7B-shape (h=4096) config when it ran — BASELINE
    # config #5's hidden sizes — else the 738M config
    if out.get("llama_mfu") is not None:
        out["llama_738m_mfu"] = out["llama_mfu"]
    if out.get("llama_h4096_mfu"):
        out["llama_mfu"] = out["llama_h4096_mfu"]

    if on_accel and out.get("hw_gemm_tfs_measured") and out.get("llama_mfu"):
        out["llama_mfu_vs_measured_peak"] = round(
            out["llama_mfu"] * (V5E_PEAK_FLOPS / 1e12) / out["hw_gemm_tfs_measured"], 4)

    # ResNet vs the chip's own conv ability (RESNET_BREAKDOWN.md)
    if on_accel and out.get("resnet50_images_per_sec") and out.get("hw_conv_tfs_measured"):
        eff = out["resnet50_images_per_sec"] * 3 * 4.1e9 / 1e12
        out["resnet50_effective_tfs"] = round(eff, 1)
        out["resnet50_frac_of_conv_ceiling"] = round(
            eff / out["hw_conv_tfs_measured"], 3)

    # decode roofline closure: floor = stream bytes / measured read bandwidth;
    # frac = floor / achieved (<= 1.0 when the accounting is consistent)
    bw = out.get("hw_hbm_gbs_measured")
    if on_accel and bw:
        for pre in ("llama_decode", "llama_decode_int8"):
            ms = out.get(f"{pre}_ms_per_token")
            gb = out.get(f"{pre}_stream_gb_per_tok")
            if ms and gb:
                floor = gb / bw * 1000
                out[f"{pre}_floor_ms_per_token"] = round(floor, 2)
                out[f"{pre}_roofline_frac"] = round(floor / ms, 3)

    mfu = out.get("llama_mfu", 0.0)
    print(json.dumps({
        "metric": "llama_pretrain_mfu" if on_accel else "llama_pretrain_mfu_cpu_smoke",
        "value": mfu,
        "unit": "model_flops_utilization",
        "vs_baseline": round(mfu / 0.70, 4),
        "timing": "median_of_3_windows",
        **out,
    }))


if __name__ == "__main__":
    main()
