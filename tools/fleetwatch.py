#!/usr/bin/env python
"""fleetwatch: scrape a fleet of telemetry endpoints, evaluate alert rules,
and render a status table — the operator CLI of the alerting plane
(README §Observability, "Alerting").

Usage::

    python tools/fleetwatch.py HOST:PORT [HOST:PORT ...]
        [--timeout 2.0] [--retries 1] [--probe-health]
        [--rules rules.json] [--no-default-rules]
        [--json] [--watch] [--interval 10] [--iterations N]
        [--log alerts.jsonl]
    python tools/fleetwatch.py --routerz HOST:PORT [--json]
    python tools/fleetwatch.py --procz HOST:PORT [--json]
    python tools/fleetwatch.py --selftest

One shot by default: scrape every target once (per-target monotonic
deadline — a dead replica cannot block the table), evaluate the rule set
(defaults: `observability.alerts.default_rules()`; `--rules` adds/replaces
from a JSON list of rule dicts), print targets + alert states.  `--watch`
re-polls every `--interval` seconds until interrupted (`--iterations`
bounds it for scripting).  `--json` emits the machine-readable form of the
same payload `/alertz` serves, plus per-target scrape results.

`--routerz HOST:PORT` asks a serving router (inference.router.Router run
with ``metrics_port=``) for its `/routerz` document and renders the fleet
view: per-replica up/draining/quarantined state, affinity-table occupancy
and hit ratio, shed and retry counts.  Exit 0 when every replica is
routable, 1 otherwise.

`--procz HOST:PORT` asks a process-fleet supervisor (``fleetserve
--procs``) for its `/procz` document and renders the supervision view:
per-child pid, incarnation, restart count, supervisor state
(starting/ready/backoff/quarantined), and the SIGKILL escalation count.
Exit 0 when every child is ready, 1 otherwise.

`--selftest` runs the embedded acceptance corpus: a canned Prometheus
exposition (escapes, histograms, +Inf) must parse sample-for-sample, a
registry render must round-trip, and a scripted sample sequence must walk
the alert state machine through the golden
inactive->pending->firing->resolved transition order.  Exit 0 = healthy —
run it on a new deployment before trusting the alerts.

Exit code (non-selftest): 0 when nothing is firing and every target is up,
1 when any alert is firing or any target is down — wire it straight into a
cron/systemd health gate.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _imports():
    from paddle_tpu.observability import alerts, scrape
    return scrape, alerts


# ------------------------------------------------------------------ render
def _fmt_age(seconds):
    if seconds is None:
        return "-"
    if seconds < 120:
        return f"{seconds:.0f}s"
    if seconds < 7200:
        return f"{seconds / 60:.0f}m"
    return f"{seconds / 3600:.1f}h"


def _target_extras(samples, name, wall_now):
    """(hbm%, last-compile age, goodput%) for one scrape target — dashes
    when the target predates the profiling plane (PR 14) / goodput
    ledger (PR 20) or runs on a backend with no memory_stats.  A dash is
    load-bearing: 0% goodput means "all waste", a real alarm, so an
    absent family must never render as 0."""
    hbm, age, goodput = "-", "-", "-"
    if samples is not None:
        hits = samples.match("hbm_utilization_ratio", {"target": name})
        if hits:
            hbm = f"{max(v for _, v in hits) * 100:.0f}%"
        hits = samples.match("jit_last_compile_unix_seconds",
                             {"target": name})
        stamp = max((v for _, v in hits), default=0.0)
        if stamp > 0 and wall_now is not None:
            age = _fmt_age(max(0.0, wall_now - stamp))
        hits = samples.match("goodput_ratio", {"target": name})
        if hits:  # worst domain: a train+serve colocation shows its pain
            goodput = f"{min(v for _, v in hits) * 100:.0f}%"
    return hbm, age, goodput


def render_status(results, state, now, samples=None, wall_now=None):
    """Text status table: targets first, then every non-inactive alert."""
    lines = ["TARGET                        UP  DURATION  ATTEMPTS  "
             "HBM%  COMPILED  GOODPUT  ERROR"]
    for r in results:
        hbm, age, goodput = _target_extras(samples, r.target.name,
                                           wall_now)
        lines.append(
            f"{r.target.name:<28}  {'up' if r.ok else 'DOWN':<4}"
            f"{r.duration_s * 1000:7.1f}ms  {r.attempts:>8}  "
            f"{hbm:>4}  {age:>8}  {goodput:>7}  "
            f"{(r.error or '-')[:40]}")
    lines.append("")
    lines.append("ALERT                      STATE     SINCE  VALUE"
                 "     LABELS")
    quiet = 0
    for a in state["alerts"]:
        live = [i for i in a["instances"] if i["state"] != "inactive"]
        if not live:
            quiet += 1
            continue
        for i in live:
            labels = ",".join(f"{k}={v}" for k, v in
                              sorted(i["labels"].items()))
            val = "-" if i["value"] is None else f"{i['value']:.4g}"
            lines.append(
                f"{a['name']:<25}  {i['state']:<8}"
                f"{_fmt_age(max(0.0, now - i['since'])):>7}  {val:<8}  "
                f"{labels[:48]}")
    lines.append(f"({quiet} rule(s) quiet)")
    return "\n".join(lines)


def render_routerz(doc):
    """Text fleet view of a router's /routerz document."""
    aff = doc.get("affinity", {})
    lines = ["REPLICA                       STATE        TARGET"
             "                 RESTARTS  HBM%  COMPILED  GOODPUT  KVTIERS"]
    for r in doc.get("replicas", []):
        # pre-PR-14 routers omit these keys — render dashes, never crash
        hbm = r.get("hbm_utilization_ratio")
        hbm = f"{hbm * 100:.0f}%" if hbm is not None else "-"
        age = _fmt_age(r.get("last_compile_age_s"))
        # pre-PR-20 replicas omit goodput_ratio — dash, never 0%
        gp = r.get("goodput_ratio")
        gp = f"{gp * 100:.0f}%" if gp is not None else "-"
        # pre-PR-19 replicas (or tiers off) omit kv_tiers entirely
        tiers = r.get("kv_tiers")
        if tiers is None:
            kvt = "-"
        else:
            mb = tiers.get("host_pool_bytes", 0) / 1e6
            ratio = tiers.get("lower_tier_hit_ratio")
            kvt = f"{mb:.1f}MB"
            if ratio is not None:
                kvt += f"/{ratio * 100:.0f}%"
        lines.append(f"{r['name']:<28}  {r['state']:<11}"
                     f"  {r['target']:<20}  {r.get('restarts', 0):>8}"
                     f"  {hbm:>4}  {age:>8}  {gp:>7}  {kvt:>7}")
    lines.append("")
    occupancy = (f"{aff.get('entries', 0)}/{aff.get('capacity', 0)}"
                 if aff.get("capacity") else "0/0")
    lines.append(
        f"affinity: {occupancy} entries"
        f"  hit_ratio={aff.get('hit_ratio', 0.0):.3f}"
        f"  (hits={aff.get('hits', 0)} misses={aff.get('misses', 0)}"
        f"  blocks={aff.get('blocks', '-')}"
        f" page_size={aff.get('page_size', '-')})")
    lines.append(f"shed: {doc.get('shed', 0)}"
                 f"   retries: {doc.get('retries', 0)}"
                 f"   overhead: {doc.get('overhead_us_mean', 0.0)}us/req")
    return "\n".join(lines)


def run_routerz(target, timeout, as_json):
    import urllib.request

    url = target if "//" in target else f"http://{target}"
    with urllib.request.urlopen(f"{url.rstrip('/')}/routerz",
                                timeout=timeout) as resp:
        doc = json.loads(resp.read())
    if as_json:
        print(json.dumps(doc, default=repr))
    else:
        print(render_routerz(doc))
    return 0 if all(r.get("state") == "up"
                    for r in doc.get("replicas", [])) else 1


def render_procz(doc):
    """Text supervision view of a fleet supervisor's /procz document."""
    lines = ["REPLICA                       STATE         PID      "
             "INC  RESTARTS  FLAPS"]
    for r in doc.get("replicas", []):
        pid = "-" if r.get("pid") is None else str(r["pid"])
        lines.append(f"{r['name']:<28}  {r['state']:<12}  {pid:<7}"
                     f"  {r.get('incarnation', 0):>3}"
                     f"  {r.get('restarts', 0):>8}"
                     f"  {r.get('deaths_in_window', 0):>5}")
    lines.append("")
    lines.append(f"engine: {doc.get('model', '-')}"
                 f"   sigkill escalations: {doc.get('escalations', 0)}")
    return "\n".join(lines)


def run_procz(target, timeout, as_json):
    import urllib.request

    url = target if "//" in target else f"http://{target}"
    with urllib.request.urlopen(f"{url.rstrip('/')}/procz",
                                timeout=timeout) as resp:
        doc = json.loads(resp.read())
    if as_json:
        print(json.dumps(doc, default=repr))
    else:
        print(render_procz(doc))
    return 0 if all(r.get("state") == "ready"
                    for r in doc.get("replicas", [])) else 1


def load_rules(args, alerts_mod):
    rules = [] if args.no_default_rules else alerts_mod.default_rules()
    if args.rules:
        with open(args.rules) as f:
            extra = [alerts_mod.Rule.from_dict(d) for d in json.load(f)]
        byname = {r.name: r for r in rules}
        for r in extra:  # file rules replace same-named defaults
            byname[r.name] = r
        rules = list(byname.values())
    return rules


def run_once(scraper, engine, as_json):
    samples, results = scraper.poll()
    engine.evaluate(samples)
    state = engine.state()
    firing = engine.firing()
    if as_json:
        print(json.dumps({
            "targets": [r.to_dict() for r in results],
            "firing": firing, **state}, default=repr))
    else:
        print(render_status(results, state, now=time.monotonic(),
                            samples=samples, wall_now=time.time()))
    unhealthy = bool(firing) or any(not r.ok for r in results)
    return 1 if unhealthy else 0


# ---------------------------------------------------------------- selftest
#: Canned exposition corpus: escaped HELP + label values, a histogram with
#: +Inf, an untyped family, a `}` inside a label value, and a timestamped
#: sample (legal exposition noise a strict parser must tolerate).
SELFTEST_CORPUS = """\
# HELP demo_requests_total Requests with \\\\ backslash and\\nnewline
# TYPE demo_requests_total counter
demo_requests_total{path="/a\\"b}c",code="200"} 42
demo_requests_total{path="plain",code="500"} 3
# TYPE demo_lat_seconds histogram
# HELP demo_lat_seconds Latency
demo_lat_seconds_bucket{op="x",le="0.1"} 1
demo_lat_seconds_bucket{op="x",le="1"} 3
demo_lat_seconds_bucket{op="x",le="+Inf"} 4
demo_lat_seconds_sum{op="x"} 5.5
demo_lat_seconds_count{op="x"} 4
untyped_thing_value 7 1700000000000
"""


def selftest():
    scrape, alerts = _imports()
    from paddle_tpu.observability.metrics import MetricRegistry

    # 1. canned corpus parses sample-for-sample
    fam = scrape.parse_prometheus(SELFTEST_CORPUS)
    assert fam["demo_requests_total"]["kind"] == "counter"
    assert fam["demo_requests_total"]["help"] == \
        "Requests with \\ backslash and\nnewline"
    s = scrape.SampleSet().add_families(fam)
    assert s.value("demo_requests_total",
                   {"path": '/a"b}c', "code": "200"}) == 42.0
    assert s.value("demo_lat_seconds_bucket",
                   {"op": "x", "le": "+Inf"}) == 4.0
    assert s.value("demo_lat_seconds_sum", {"op": "x"}) == 5.5
    assert s.value("untyped_thing_value") == 7.0
    assert fam["untyped_thing_value"]["kind"] == "untyped"

    # 2. render -> parse round-trip on a live registry
    reg = MetricRegistry()
    reg.counter("st_total", "selftest", labelnames=("k",)) \
        .labels(k='we"ird\n').inc(2)
    reg.histogram("st_seconds", "selftest", buckets=(0.5,)).observe(0.25)
    assert scrape.parse_prometheus(reg.render_prometheus()) \
        == reg.snapshot()

    # 3. golden state-machine walk under an injected clock
    rule = alerts.Rule("st_hc", metric="healthcheck_status_value",
                       op="<", threshold=1.0, for_s=10.0,
                       resolved_hold_s=20.0)
    eng = alerts.AlertEngine(rules=[rule], clock=lambda: 0.0)

    def at(t, v):
        ss = scrape.SampleSet()
        ss.add("healthcheck_status_value", {"check": "w"}, v)
        return [(t, tr["from"], tr["to"])
                for tr in eng.evaluate(ss, now=t)]

    seq = []
    for t, v in [(0, 1.0), (5, 0.0), (10, 0.0), (16, 0.0),
                 (25, 1.0), (30, 0.0), (41, 0.0), (45, 1.0), (70, 1.0)]:
        seq += at(t, v)
    golden = [
        (5, "inactive", "pending"), (16, "pending", "firing"),
        (25, "firing", "resolved"),
        (30, "resolved", "pending"), (41, "pending", "firing"),  # flap
        (45, "firing", "resolved"), (70, "resolved", "inactive"),
    ]
    assert seq == golden, f"state machine diverged: {seq}"
    print("fleetwatch selftest: ok "
          f"({len(SELFTEST_CORPUS.splitlines())} corpus lines, "
          f"{len(golden)} golden transitions)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("targets", nargs="*", metavar="HOST:PORT",
                    help="telemetry endpoints to scrape (/metrics)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-target scrape budget, seconds (monotonic)")
    ap.add_argument("--retries", type=int, default=1)
    ap.add_argument("--probe-health", action="store_true",
                    help="GET /healthz before /metrics on every target "
                         "(refreshes healthcheck_status_value gauges)")
    ap.add_argument("--rules", help="JSON file: list of rule dicts "
                                    "(replace same-named defaults)")
    ap.add_argument("--no-default-rules", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--watch", action="store_true")
    ap.add_argument("--interval", type=float, default=10.0)
    ap.add_argument("--iterations", type=int, default=0,
                    help="with --watch: stop after N polls (0 = forever)")
    ap.add_argument("--log", help="append alert transitions to this JSONL")
    ap.add_argument("--routerz", metavar="HOST:PORT",
                    help="render a serving router's /routerz fleet view "
                         "instead of scraping targets")
    ap.add_argument("--procz", metavar="HOST:PORT",
                    help="render a process-fleet supervisor's /procz "
                         "supervision view instead of scraping targets")
    ap.add_argument("--selftest", action="store_true")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.routerz:
        return run_routerz(args.routerz, args.timeout, args.as_json)
    if args.procz:
        return run_procz(args.procz, args.timeout, args.as_json)
    if not args.targets:
        ap.error("need at least one HOST:PORT target (or --selftest)")

    scrape, alerts = _imports()
    scraper = scrape.Scraper(
        [scrape.ScrapeTarget(t, probe_health=args.probe_health)
         for t in args.targets],
        timeout_s=args.timeout, retries=args.retries)
    engine = alerts.AlertEngine(rules=load_rules(args, alerts),
                                log_path=args.log)

    rc = run_once(scraper, engine, args.as_json)
    polls = 1
    while args.watch and (args.iterations <= 0 or polls < args.iterations):
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            break
        if not args.as_json:
            print()
        rc = run_once(scraper, engine, args.as_json)
        polls += 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
