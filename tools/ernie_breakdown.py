"""ERNIE b512xs128 step-time breakdown via ablation (round-4 verdict #1).

Where do the ~700 ms of the ERNIE pretrain step go?  Times the compiled
TrainStep under a ladder of ablations (dropout off, heads off, forward
only) plus targeted microbenches (threefry vs rbg RNG, embedding-bwd
scatter), RTT-corrected per the tunnel-timing rules in bench.py.

Run:  python tools/ernie_breakdown.py            # prints a JSON dict
"""
from __future__ import annotations

import dataclasses
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

BATCH, SEQ, STEPS, WINDOWS = 512, 128, 8, 3
_RTT_S = 0.0


def _measure_rtt():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((8, 8), jnp.float32)
    f = jax.jit(lambda v: v + 1.0)
    _ = np.asarray(f(x))
    s = []
    for _i in range(5):
        t0 = time.perf_counter()
        _ = np.asarray(f(x))
        s.append(time.perf_counter() - t0)
    return sorted(s)[2]


def _time_step(step_call, sync):
    """Median-of-WINDOWS window time for STEPS chained dispatches, minus RTT."""
    for _ in range(2):
        step_call()
    sync()
    ws = []
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = step_call()
        sync(out)
        ws.append(time.perf_counter() - t0)
    return max(sorted(ws)[WINDOWS // 2] - _RTT_S, 1e-6) / STEPS


def _batch(cfg):
    import paddle_tpu as paddle

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (BATCH, SEQ)).astype(np.int32))
    seg = paddle.to_tensor((rng.rand(BATCH, SEQ) > 0.5).astype(np.int32))
    mlm = rng.randint(0, cfg.vocab_size, (BATCH, SEQ)).astype(np.int32)
    mlm[rng.rand(BATCH, SEQ) > 0.15] = -100
    nsp = rng.randint(0, 2, (BATCH, 1)).astype(np.int32)
    return ids, seg, paddle.to_tensor(mlm), paddle.to_tensor(nsp)


def _build(drop=True, attn_drop=True, heads=True):
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertConfig, ErnieForPretraining

    cfg = BertConfig.base()
    if not drop:
        cfg = dataclasses.replace(cfg, hidden_dropout_prob=0.0)
    if not attn_drop:
        cfg = dataclasses.replace(cfg, attention_probs_dropout_prob=0.0)
    paddle.seed(0)
    model = ErnieForPretraining(cfg)
    model.bfloat16()
    if heads:
        def loss_fn(ids, seg, mlm_labels, nsp):
            loss, _ = model(ids, token_type_ids=seg, masked_lm_labels=mlm_labels,
                            next_sentence_label=nsp)
            return loss
    else:
        def loss_fn(ids, seg, mlm_labels, nsp):
            seq, _pooled = model.bert(ids, seg)
            return (seq.astype("float32") * seq.astype("float32")).mean()
    return cfg, model, loss_fn


def _variant_step(drop=True, attn_drop=True, heads=True):
    import paddle_tpu as paddle

    cfg, model, loss_fn = _build(drop, attn_drop, heads)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                                 parameters=model.parameters())
    step = paddle.jit.TrainStep(model, loss_fn, opt)
    ids, seg, mlm, nsp = _batch(cfg)
    call = lambda: step(ids, seg, mlm, nsp)  # noqa: E731
    sync = lambda out=None: float(out.item()) if out is not None else float(call().item())  # noqa: E731
    return call, sync


def _variant_masked(n_pred=20):
    """Reference pretrain recipe: MLM head over masked positions only."""
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertConfig, ErnieForPretraining

    cfg = BertConfig.base()
    paddle.seed(0)
    model = ErnieForPretraining(cfg)
    model.bfloat16()

    def loss_fn(ids, seg, pos, labels, nsp):
        loss, _ = model(ids, token_type_ids=seg, masked_lm_labels=labels,
                        next_sentence_label=nsp, masked_positions=pos)
        return loss

    opt = paddle.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                                 parameters=model.parameters())
    step = paddle.jit.TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (BATCH, SEQ)).astype(np.int32))
    seg = paddle.to_tensor((rng.rand(BATCH, SEQ) > 0.5).astype(np.int32))
    pos = paddle.to_tensor(
        np.stack([rng.choice(SEQ, n_pred, replace=False) for _ in range(BATCH)]).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (BATCH, n_pred)).astype(np.int32))
    nsp = paddle.to_tensor(rng.randint(0, 2, (BATCH, 1)).astype(np.int32))
    call = lambda: step(ids, seg, pos, labels, nsp)  # noqa: E731
    sync = lambda out=None: float(out.item()) if out is not None else float(call().item())  # noqa: E731
    return call, sync


def _variant_fwd(drop=True, attn_drop=None, heads=True):
    """Forward loss only (no grad, no optimizer) — same dropout/RNG work."""
    import jax

    from paddle_tpu.autograd import tape
    from paddle_tpu.framework import random as _random
    from paddle_tpu.tensor.tensor import Tensor

    if attn_drop is None:
        attn_drop = drop  # 'nodrop' means ALL dropout off, as in _variant_step
    cfg, model, loss_fn = _build(drop, attn_drop, heads)
    params, buffers = model.functional_state()
    ids, seg, mlm, nsp = _batch(cfg)
    raw = tuple(t._value for t in (ids, seg, mlm, nsp))

    def fwd(params, buffers, key, *batch):
        with _random.rng_key_scope(key):
            restore = model.bind_functional_state(params, buffers)
            try:
                with tape.no_grad():
                    args = tuple(Tensor(b, stop_gradient=True) for b in batch)
                    out = loss_fn(*args)
            finally:
                restore()
        loss = out[0] if isinstance(out, (tuple, list)) else out
        return loss._value

    jfwd = jax.jit(fwd)

    def call():
        key = _random.get_rng_key()
        return jfwd(params, buffers, key, *raw)

    sync = lambda out=None: float(np.asarray(out if out is not None else call()))  # noqa: E731
    return call, sync


def _rng_microbench(impl):
    """Cost of ONE step's worth of dropout mask generation: 25 hidden-size
    draws ([B*S, H]) + 12 attention-probs draws ([B, 12, S, S])."""
    import jax
    import jax.numpy as jnp

    key = jax.random.key(0, impl=impl)

    @jax.jit
    def draws(key):
        acc = jnp.zeros((), jnp.float32)
        for i in range(25):
            key, sub = jax.random.split(key)
            m = jax.random.bernoulli(sub, 0.9, (BATCH * SEQ, 768))
            acc = acc + jnp.sum(m[:1, :8].astype(jnp.float32))
        for i in range(12):
            key, sub = jax.random.split(key)
            m = jax.random.bernoulli(sub, 0.9, (BATCH, 12, SEQ, SEQ))
            acc = acc + jnp.sum(m[:1, :1, :1, :8].astype(jnp.float32))
        return acc

    call = lambda: draws(key)  # noqa: E731
    sync = lambda out=None: float(np.asarray(out if out is not None else call()))  # noqa: E731
    return _time_step(call, sync)


def _embed_bwd_microbench():
    """Embedding fwd+bwd in isolation: gather + scatter-add grads for the
    word/position/token-type tables at the bench shapes."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 30522, (BATCH, SEQ)), jnp.int32)
    pos = jnp.asarray(np.tile(np.arange(SEQ, dtype=np.int32), (BATCH, 1)))
    seg = jnp.asarray(rng.randint(0, 2, (BATCH, SEQ)), jnp.int32)
    w = jnp.asarray(rng.randn(30522, 768) * 0.01, jnp.bfloat16)
    wp = jnp.asarray(rng.randn(512, 768) * 0.01, jnp.bfloat16)
    wt = jnp.asarray(rng.randn(2, 768) * 0.01, jnp.bfloat16)

    def loss(w, wp, wt):
        e = jnp.take(w, ids, axis=0) + jnp.take(wp, pos, axis=0) + jnp.take(wt, seg, axis=0)
        return jnp.sum(e.astype(jnp.float32) * e.astype(jnp.float32))

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    call = lambda: g(w, wp, wt)  # noqa: E731
    sync = lambda out=None: float(np.asarray((out if out is not None else call())[0][0, 0]))  # noqa: E731
    return _time_step(call, sync)


def main():
    global _RTT_S
    import jax

    plat = jax.devices()[0].platform
    _RTT_S = _measure_rtt()
    out = {"platform": plat, "rtt_ms": round(_RTT_S * 1e3, 1),
           "batch_seq": [BATCH, SEQ]}

    def run(name, fn, *a, **kw):
        try:
            call, sync = fn(*a, **kw)
            out[f"step_ms_{name}"] = round(_time_step(call, sync) * 1e3, 1)
            print(f"# {name}: {out[f'step_ms_{name}']} ms", file=sys.stderr)
        except Exception as e:
            out[f"step_ms_{name}"] = None
            out[f"error_{name}"] = repr(e)[:160]
            print(f"# {name}: FAILED {repr(e)[:120]}", file=sys.stderr)

    run("masked", _variant_masked)
    run("full", _variant_step)
    run("nodrop", _variant_step, drop=False, attn_drop=False)
    run("noattndrop", _variant_step, attn_drop=False)
    run("encoder_only", _variant_step, heads=False)
    run("encoder_only_nodrop", _variant_step, heads=False, drop=False, attn_drop=False)
    run("fwd_only", _variant_fwd)
    run("fwd_only_nodrop", _variant_fwd, drop=False)

    out["rng_ms_threefry"] = round(_rng_microbench("threefry2x32") * 1e3, 1)
    out["rng_ms_rbg"] = round(_rng_microbench("rbg") * 1e3, 1)
    out["embed_bwd_ms"] = round(_embed_bwd_microbench() * 1e3, 1)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
