#!/usr/bin/env python
"""Metric-namespace lint (wired as a tier-1 test in tests/test_observability.py).

The observability layer registers every metric family at module import time,
so the full namespace is visible without running a workload.  This lint
walks the default registry and fails on:

- non-snake_case names (anything outside ``[a-z][a-z0-9_]*``);
- names without a recognized unit suffix (``_total``, ``_seconds``,
  ``_bytes``, ``_ratio``, ``_per_second``, ``_depth``, ``_slots``,
  ``_step``, ``_count``, ``_value``, ``_fraction``) — a unitless gauge
  named ``foo`` rots into three dashboards disagreeing about its
  dimension;
- names not documented in README.md's "## Observability" metric catalogue —
  undocumented series are invisible to operators and drift silently;
- label names that are not snake_case.

This lint is registered as tpulint rule ``metrics-catalogue`` — the
canonical CI entrypoint is ``python tools/tpulint.py --check paddle_tpu``
(one driver for every lint).  This CLI remains as a thin shim over the same
``import_instrumented()`` + ``lint()`` pair the rule calls, so the two
entrypoints cannot drift.

Usage: ``python tools/metrics_lint.py [--readme README.md]`` from the repo
root; exit code 1 on any finding.
"""
from __future__ import annotations

import argparse
import os
import re
import sys

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# `name` or `name{label,...}` — the catalogue writes labeled families with
# their label names inline
_BACKTICK_RE = re.compile(r"`([a-z][a-z0-9_]*)(?:\{[^}`]*\})?`")

#: Recognized unit suffixes.  Deliberately short: extend it here (and in the
#: README catalogue) rather than minting one-off unit spellings.  ``_up`` is
#: the Prometheus liveness-boolean convention (the scraper's
#: ``scrape_target_up{target}`` mirrors Prometheus' own ``up`` series).
UNIT_SUFFIXES = ("_total", "_seconds", "_bytes", "_ratio", "_per_second",
                 "_depth", "_slots", "_step", "_count", "_value", "_up",
                 "_fraction")


def documented_names(readme_path: str) -> set[str]:
    """Backticked identifiers inside README's '## Observability' section."""
    try:
        with open(readme_path) as f:
            text = f.read()
    except OSError:
        return set()
    m = re.search(r"^## Observability\b(.*?)(?=^## |\Z)", text,
                  re.MULTILINE | re.DOTALL)
    if not m:
        return set()
    return set(_BACKTICK_RE.findall(m.group(1)))


def lint(registry=None, readme_path: str = "README.md") -> list[str]:
    """Return a list of human-readable findings (empty = clean)."""
    if registry is None:
        from paddle_tpu.observability import REGISTRY as registry
    documented = documented_names(readme_path)
    errors = []
    for metric in registry:
        name = metric.name
        if not _NAME_RE.match(name):
            errors.append(f"{name}: not snake_case ([a-z][a-z0-9_]*)")
        if not name.endswith(UNIT_SUFFIXES):
            errors.append(
                f"{name}: missing unit suffix (expected one of "
                f"{', '.join(UNIT_SUFFIXES)})")
        if documented and name not in documented:
            errors.append(
                f"{name}: not documented in the README Observability "
                f"catalogue ({readme_path})")
        for ln in metric.labelnames:
            if not _NAME_RE.match(ln):
                errors.append(f"{name}: label {ln!r} is not snake_case")
    if not documented:
        errors.append(
            f"{readme_path}: no '## Observability' section with backticked "
            f"metric names found — the catalogue is the lint's source of "
            f"truth")
    return errors


def import_instrumented(repo_root=None):
    """Import every instrumented layer so its metric families are registered
    even if the package __init__ is ever slimmed down; return the registry.
    Shared by this CLI and the tpulint ``metrics-catalogue`` rule."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    import paddle_tpu  # noqa: F401
    import paddle_tpu.distributed.checkpoint  # noqa: F401
    import paddle_tpu.ops.decode_attention  # noqa: F401
    import paddle_tpu.distributed.fault_tolerance  # noqa: F401
    import paddle_tpu.distributed.sharded_train_step  # noqa: F401
    import paddle_tpu.distributed.store  # noqa: F401
    import paddle_tpu.hapi.callbacks  # noqa: F401
    import paddle_tpu.inference.constrain  # noqa: F401
    import paddle_tpu.inference.fleet_supervisor  # noqa: F401
    import paddle_tpu.inference.llm_server  # noqa: F401
    import paddle_tpu.inference.router  # noqa: F401
    import paddle_tpu.models.lora  # noqa: F401
    import paddle_tpu.observability.goodput  # noqa: F401
    import paddle_tpu.observability.profiling  # noqa: F401
    import paddle_tpu.observability.roofline  # noqa: F401
    import paddle_tpu.observability.xplane  # noqa: F401
    from paddle_tpu.observability import REGISTRY
    return REGISTRY


def main(argv=None) -> int:
    """Thin shim — `python tools/tpulint.py --select metrics-catalogue` is
    the canonical entrypoint; this stays for muscle memory and --readme."""
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--readme", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "README.md"))
    args = ap.parse_args(argv)

    REGISTRY = import_instrumented()
    errors = lint(REGISTRY, args.readme)
    if errors:
        print(f"metrics_lint: {len(errors)} finding(s):")
        for e in errors:
            print(f"  - {e}")
        return 1
    print(f"metrics_lint: {len(REGISTRY.names())} metric families clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
