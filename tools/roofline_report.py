#!/usr/bin/env python
"""roofline_report — per-HLO measured-vs-predicted residual table and the
perf-regression sentinel (paddle_tpu.observability.roofline as a CLI).

Measure mode — join one profiler dump against one census into a residual
round::

    python tools/roofline_report.py --xplane prof/ --census per_op.json \
        --round r02_tpu --out .

    --xplane dump       `jax.profiler.trace()` dump: a `.xplane.pb` file
                        or any logdir above one (per-HLO device µs)
    --census f.json     per-op cost table (census.per_op_census rows or a
                        {name: {flops, bytes}} mapping)
    --peak-flops N      roofline FLOP/s denominator (default:
                        cost_model.peak_flops_per_device)
    --peak-bw N         roofline HBM bytes/s denominator (default:
                        cost_model.peak_hbm_bytes_per_sec)
    --round NAME        also persist as ROOFLINE_<NAME>.json under --out
    --out DIR           where --round writes (default: repo root)
    --top K             rows to print (default 20; persisted rounds keep
                        every row)
    --json out.json     write the report document here too

Diff mode — the sentinel::

    python tools/roofline_report.py --diff OLD.json [NEW.json] \
        [--threshold 0.25] [--min-us 50]

With one argument the round is compared against the lexically-newest
committed ``ROOFLINE_*.json`` (itself excluded) — the cron one-liner.
An op REGRESSES when its residual ratio grew by more than ``--threshold``
(relative) AND its wasted µs grew by more than ``--min-us`` (absolute).

Exit codes: 0 usable table / clean diff; 1 nothing to attribute (or no
baseline to diff against); 2 = the sentinel tripped — a census that
joined zero timed rows in measure mode, or ≥1 regressed op in diff mode.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

__all__ = ["main"]

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _plane():
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu.observability import roofline
    return roofline


def _measure(args) -> int:
    roofline = _plane()
    from paddle_tpu.observability import xplane
    sys.path[0:0] = [os.path.join(_REPO, "tools")]
    import trace_report
    measured = xplane.per_op_summary(xplane.load_xspace(
        xplane.find_dump(args.xplane)))
    census = trace_report.load_census(args.census) if args.census else {}
    pf, pbw = args.peak_flops, args.peak_bw
    if pf is None or pbw is None:
        from paddle_tpu import cost_model
        pf = cost_model.peak_flops_per_device() if pf is None else pf
        pbw = cost_model.peak_hbm_bytes_per_sec() if pbw is None else pbw
    report = roofline.build_report(
        measured, census, pf, pbw,
        config={"xplane": os.path.basename(str(args.xplane)),
                "census": os.path.basename(str(args.census or ""))})
    if not report["rows"]:
        print("roofline_report: no timed events and no census ops — "
              "nothing to attribute")
        return 1
    print(roofline.render_text(report, top=args.top))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"wrote report to {args.json_out}")
    if args.round:
        path = roofline.save_round(report, args.out or _REPO, args.round)
        print(f"persisted round as {path} (key {report['key']})")
    if census and report["summary"]["timed_matched_ops"] == 0:
        print("roofline_report: census joined zero timed rows — the "
              "profile and the cost model do not describe the same "
              "program", file=sys.stderr)
        timed = [r for r in report["rows"] if r["measured_us"] > 0]
        costed = [r for r in report["rows"]
                  if r["measured_us"] == 0
                  and (r["flops"] > 0 or r["bytes"] > 0)]
        costed.sort(key=lambda r: (-r["flops"], -r["bytes"]))
        for label, side in (("measured", timed), ("census", costed)):
            names = ", ".join(r["name"] for r in side[:5]) or "(empty)"
            print(f"  unmatched {label} names (top {min(5, len(side))}): "
                  f"{names}", file=sys.stderr)
        return 2
    return 0


def _diff(args) -> int:
    roofline = _plane()
    old_path = args.diff[0]
    if len(args.diff) > 1:
        new_path = args.diff[1]
    else:
        # one argument = compare against the newest committed baseline
        # (excluding the argument itself), oldest side first
        new_path = old_path
        old_path = roofline.newest_round(args.out or _REPO,
                                         exclude=new_path)
        if old_path is None:
            print("roofline_report: no committed ROOFLINE_*.json "
                  "baseline to diff against", file=sys.stderr)
            return 1
    diff = roofline.diff_reports(roofline.load_round(old_path),
                                 roofline.load_round(new_path),
                                 threshold=args.threshold,
                                 min_us=args.min_us)
    print(f"diff {os.path.basename(old_path)} -> "
          f"{os.path.basename(new_path)}")
    print(roofline.render_diff_text(diff))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(diff, f, indent=1, sort_keys=True)
    return 2 if roofline.record_diff(diff) else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--xplane",
                      help="profiler dump (.xplane.pb file or logdir): "
                           "measure mode")
    mode.add_argument("--diff", nargs="+", metavar="ROUND.json",
                      help="diff mode: OLD NEW, or one round against the "
                           "newest committed ROOFLINE_*.json baseline")
    ap.add_argument("--census", default=None,
                    help="per-op census JSON (measure mode)")
    ap.add_argument("--peak-flops", type=float, default=None)
    ap.add_argument("--peak-bw", type=float, default=None)
    ap.add_argument("--round", default=None,
                    help="persist the report as ROOFLINE_<NAME>.json")
    ap.add_argument("--out", default=None,
                    help="directory for --round / baseline discovery "
                         "(default: repo root)")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the report / diff document here")
    ap.add_argument("--threshold", type=float, default=None,
                    help="relative residual-growth threshold "
                         "(default 0.25)")
    ap.add_argument("--min-us", type=float, default=None,
                    help="absolute wasted-µs floor for a regression "
                         "(default 50)")
    args = ap.parse_args(argv)
    roofline = _plane()
    if args.threshold is None:
        args.threshold = roofline.DEFAULT_THRESHOLD
    if args.min_us is None:
        args.min_us = roofline.DEFAULT_MIN_US
    if args.diff:
        if len(args.diff) > 2:
            ap.error("--diff takes one or two round files")
        return _diff(args)
    return _measure(args)


if __name__ == "__main__":
    sys.exit(main())
