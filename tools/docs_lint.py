#!/usr/bin/env python
"""Docs staleness lint (tpulint rule `docs-stale`; standalone CLI kept).

PROJECTION.md's pod-scale estimates are anchored to measured single-chip
rates from a ``BENCH_r*.json`` round.  ``tools/project_pod.py`` always reads
the NEWEST round (lexically last glob match), so a PROJECTION.md citing an
older round is stale output that no longer matches what the generator would
produce — the projections and the measurements have drifted apart.

Check: the basename stem of the newest ``BENCH_r*.json`` (e.g. ``BENCH_r05``)
must appear in PROJECTION.md.  Fix: ``python tools/project_pod.py --validate
--write``.

Usage: ``python tools/docs_lint.py [--root DIR]``; exit 1 on findings.
"""
from __future__ import annotations

import argparse
import glob
import os
import re
import sys

_BENCH_CITE_RE = re.compile(r"BENCH_r[0-9][0-9a-z_]*")


def newest_bench(root: str):
    """Basename of the newest bench round, or None.  Lexical sort matches
    tools/project_pod.py's ``paths[-1]`` — the two must agree on 'newest'."""
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    return os.path.basename(paths[-1]) if paths else None


def check(root: str):
    """Return findings as (relpath, line, message) tuples; empty = clean."""
    newest = newest_bench(root)
    proj = os.path.join(root, "PROJECTION.md")
    if newest is None or not os.path.exists(proj):
        return []
    stem = newest[:-len(".json")] if newest.endswith(".json") else newest
    with open(proj, encoding="utf-8") as f:
        lines = f.read().splitlines()
    cited_lines = []  # (lineno, {stems cited on that line})
    for i, line in enumerate(lines, 1):
        hits = set(_BENCH_CITE_RE.findall(line))
        if hits:
            cited_lines.append((i, hits))
    all_cited = set().union(*(h for _, h in cited_lines)) if cited_lines \
        else set()
    if stem in all_cited:
        return []
    if not cited_lines:
        return [("PROJECTION.md", 1,
                 f"cites no BENCH round at all — newest is {newest}; "
                 f"regenerate with `python tools/project_pod.py --validate "
                 f"--write`")]
    line_no, stale = cited_lines[0]
    return [("PROJECTION.md", line_no,
             f"cites {sorted(stale)[0]} but the newest bench round is "
             f"{newest} — regenerate with `python tools/project_pod.py "
             f"--validate --write`")]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    args = ap.parse_args(argv)
    findings = check(args.root)
    for path, line, msg in findings:
        print(f"{path}:{line}: docs-stale {msg}")
    if not findings:
        print("docs_lint: PROJECTION.md cites the newest bench round "
              f"({newest_bench(args.root)})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
