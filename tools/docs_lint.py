#!/usr/bin/env python
"""Docs staleness lint (tpulint rule `docs-stale`; standalone CLI kept).

PROJECTION.md's pod-scale estimates are anchored to measured single-chip
rates from a ``BENCH_r*.json`` round.  ``tools/project_pod.py`` always reads
the NEWEST round (lexically last glob match), so a PROJECTION.md citing an
older round is stale output that no longer matches what the generator would
produce — the projections and the measurements have drifted apart.

Checks (each absent-tolerant: no rounds on disk = nothing to cite):

- the basename stem of the newest ``BENCH_r*.json`` (e.g. ``BENCH_r05``)
  must appear in PROJECTION.md;
- once a ``ROOFLINE_*.json`` residual round exists (the roofline plane's
  content-addressed artifact), the newest one's stem must appear too —
  the projections cite the measured-vs-predicted round they were checked
  against, same idiom as the BENCH anchor.

Fix for both: ``python tools/project_pod.py --validate --write``.

Usage: ``python tools/docs_lint.py [--root DIR]``; exit 1 on findings.
"""
from __future__ import annotations

import argparse
import glob
import os
import re
import sys

_BENCH_CITE_RE = re.compile(r"BENCH_r[0-9][0-9a-z_]*")
_ROOFLINE_CITE_RE = re.compile(r"ROOFLINE_r[0-9][0-9a-z_]*")


def newest_bench(root: str):
    """Basename of the newest bench round, or None.  Lexical sort matches
    tools/project_pod.py's ``paths[-1]`` — the two must agree on 'newest'."""
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    return os.path.basename(paths[-1]) if paths else None


def newest_roofline(root: str):
    """Basename of the newest roofline residual round, or None (same
    lexical-sort contract as ``newest_bench`` /
    ``observability.roofline.newest_round``)."""
    paths = sorted(glob.glob(os.path.join(root, "ROOFLINE_*.json")))
    return os.path.basename(paths[-1]) if paths else None


def _check_citation(lines, newest, cite_re, what):
    """One round-family citation check -> findings list."""
    stem = newest[:-len(".json")] if newest.endswith(".json") else newest
    cited_lines = []  # (lineno, {stems cited on that line})
    for i, line in enumerate(lines, 1):
        hits = set(cite_re.findall(line))
        if hits:
            cited_lines.append((i, hits))
    all_cited = set().union(*(h for _, h in cited_lines)) if cited_lines \
        else set()
    if stem in all_cited:
        return []
    if not cited_lines:
        return [("PROJECTION.md", 1,
                 f"cites no {what} round at all — newest is {newest}; "
                 f"regenerate with `python tools/project_pod.py --validate "
                 f"--write`")]
    line_no, stale = cited_lines[0]
    return [("PROJECTION.md", line_no,
             f"cites {sorted(stale)[0]} but the newest {what} round is "
             f"{newest} — regenerate with `python tools/project_pod.py "
             f"--validate --write`")]


def check(root: str):
    """Return findings as (relpath, line, message) tuples; empty = clean."""
    proj = os.path.join(root, "PROJECTION.md")
    if not os.path.exists(proj):
        return []
    with open(proj, encoding="utf-8") as f:
        lines = f.read().splitlines()
    findings = []
    bench = newest_bench(root)
    if bench is not None:
        findings.extend(_check_citation(lines, bench, _BENCH_CITE_RE,
                                        "bench"))
    roofline = newest_roofline(root)
    if roofline is not None:
        findings.extend(_check_citation(lines, roofline,
                                        _ROOFLINE_CITE_RE, "roofline"))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    args = ap.parse_args(argv)
    findings = check(args.root)
    for path, line, msg in findings:
        print(f"{path}:{line}: docs-stale {msg}")
    if not findings:
        print("docs_lint: PROJECTION.md cites the newest rounds "
              f"(bench {newest_bench(args.root)}, roofline "
              f"{newest_roofline(args.root)})")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
