#!/usr/bin/env python
"""tpulint — TPU-native static-analysis driver (tier-1 gate).

Polices trace-safety, collectives, and dtype discipline across the package:
host syncs inside jitted steps, impure traces, mesh-axis typos, donated
buffers read after the call, f32 drift in bf16 paths, exported no-ops,
swallowed faults in recovery code, the metric-namespace catalogue, and docs
staleness.  Rule catalogue: README §Static analysis;
engine: ``paddle_tpu/analysis/``.

Usage::

    python tools/tpulint.py --check paddle_tpu          # the tier-1 gate
    python tools/tpulint.py --list-rules                # + last-run counts
    python tools/tpulint.py path/ --format json
    python tools/tpulint.py --check paddle_tpu --select impure-trace
    python tools/tpulint.py --check paddle_tpu --write-baseline /tmp/b.json
    python tools/tpulint.py --changed                   # touched vs HEAD
    python tools/tpulint.py --check paddle_tpu --jobs 4 # parallel file pass
    python tools/tpulint.py --explain blocking-under-lock

Exit codes: 0 clean, 1 findings at/above --fail-on, 2 usage/baseline error.

Suppress a single line with ``# tpulint: disable=rule-name`` (or ``=all``);
grandfather history in ``tools/tpulint_baseline.json`` — every entry MUST
carry a one-line justification or the driver refuses to run.

The engine is loaded by file path under a private module name so linting
works even when ``import paddle_tpu`` itself is broken — a linter that needs
the patient healthy is not a diagnostic tool.  (The metrics-catalogue rule
does import the live package, and degrades to a note if it cannot.)
"""
from __future__ import annotations

import argparse
import hashlib
import importlib.util
import json
import os
import subprocess
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_analysis():
    """Load paddle_tpu/analysis as a standalone package (no paddle_tpu
    __init__, no jax import)."""
    name = "_tpulint_analysis"
    if name in sys.modules:
        return sys.modules[name]
    pkg_dir = os.path.join(REPO_ROOT, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _resolve_root(targets):
    """Repo root when every target lives inside it, else the CWD — lets the
    same driver lint fixture trees in tests."""
    abs_targets = [os.path.abspath(t) for t in targets]
    if all(t.startswith(REPO_ROOT + os.sep) or t == REPO_ROOT
           for t in abs_targets):
        return REPO_ROOT
    return os.getcwd()


def _changed_files(root, ref):
    """Root-relative ``.py`` paths touched vs ``ref`` plus untracked ones —
    the file set a pre-push spot-lint cares about.  Returns None (not [])
    when git itself is unusable so the caller can distinguish "nothing
    changed" from "cannot tell"."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "--diff-filter=d", ref],
            cwd=root, capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0:
        return None
    names = diff.stdout.splitlines() + untracked.stdout.splitlines()
    out = []
    for n in sorted(set(names)):
        if n.endswith(".py") and os.path.exists(os.path.join(root, n)):
            out.append(n)
    return out


def _parallel_worker(payload):
    """Pool entry: file-rule lint over one chunk of ``(abspath, relpath)``
    pairs.  Module-level (picklable) and self-loading so it works under both
    fork and spawn start methods."""
    root, pairs, select, ignore = payload
    analysis = load_analysis()
    return analysis.run_files(root, pairs,
                              select=set(select) if select else None,
                              ignore=set(ignore) if ignore else None)


def _run_parallel(analysis, root, targets, select, ignore, project_rules,
                  jobs):
    """``--jobs N``: file rules fan out across a process pool; project rules
    (which need the whole tree + possibly the live package) stay in the
    parent.  Chunks preserve walk order and the final sort uses the same
    key as the serial runner, so output is byte-identical to ``--jobs 1``."""
    import multiprocessing

    pairs = analysis.list_target_files(root, targets)
    jobs = max(1, min(int(jobs), len(pairs) or 1))
    chunks = [pairs[i::jobs] for i in range(jobs)]
    # round-robin balances big/small files; order restored by the sort below
    payloads = [(root, c, sorted(select) if select else None,
                 sorted(ignore) if ignore else None)
                for c in chunks if c]
    with multiprocessing.Pool(processes=jobs) as pool:
        dict_lists = pool.map(_parallel_worker, payloads)
    findings = [analysis.Finding(**d) for dl in dict_lists for d in dl]
    if project_rules:
        project = analysis.ProjectContext(os.path.abspath(root))
        project.lint_targets = [
            t if os.path.isabs(t) else os.path.join(root, t)
            for t in (targets or [root])]
        findings.extend(analysis.project_rule_findings(project, select,
                                                       ignore))
    findings.sort(key=analysis.finding_sort_key)
    return findings


def _counts_path(root):
    """Per-root scratch file for ``--list-rules`` finding counts — keyed by
    the root path so parallel checkouts don't clobber each other."""
    digest = hashlib.sha256(os.path.abspath(root).encode()).hexdigest()[:16]
    return os.path.join(tempfile.gettempdir(), f"tpulint_counts_{digest}.json")


def _save_counts(root, findings, baselined):
    counts = {}
    for f in findings:
        counts.setdefault(f.rule, {"open": 0, "baselined": 0})["open"] += 1
    for f in baselined:
        counts.setdefault(f.rule, {"open": 0, "baselined": 0})[
            "baselined"] += 1
    try:
        with open(_counts_path(root), "w", encoding="utf-8") as fh:
            json.dump({"root": os.path.abspath(root), "counts": counts}, fh)
    except OSError:
        pass  # counts are a convenience; never fail the lint over them


def _load_counts(root):
    try:
        with open(_counts_path(root), encoding="utf-8") as fh:
            return json.load(fh).get("counts", {})
    except (OSError, ValueError):
        return {}


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="tpulint", description=__doc__.splitlines()[0].strip())
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument("--check", action="append", default=[], metavar="PATH",
                    help="path to lint (alias for a positional path; the "
                         "tier-1 invocation is --check paddle_tpu)")
    ap.add_argument("--root", default=None,
                    help="project root for relative paths/baseline "
                         "(default: auto)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: <root>/tools/"
                         "tpulint_baseline.json when present)")
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--select", action="append", metavar="RULE",
                    help="run only these rules (repeatable)")
    ap.add_argument("--ignore", action="append", metavar="RULE",
                    help="skip these rules (repeatable)")
    ap.add_argument("--fail-on", choices=("error", "warning"),
                    default="warning",
                    help="lowest severity that fails the run (default: "
                         "warning; notes never fail)")
    ap.add_argument("--list-rules", action="store_true",
                    help="list the rule catalogue, with per-rule finding "
                         "counts from the last --check of this root")
    ap.add_argument("--explain", metavar="RULE",
                    help="print a rule's full documentation (severity, "
                         "scope, rationale, true/false-positive examples)")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="lint only files changed vs REF (default HEAD) "
                         "plus untracked files — the pre-push spot-lint")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="run file rules across N worker processes "
                         "(output is byte-identical to the serial run)")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write current findings as baseline entries (each "
                         "needs its justification filled in before the "
                         "loader will accept it)")
    args = ap.parse_args(argv)

    analysis = load_analysis()

    if args.explain:
        rule = analysis.RULES.get(args.explain)
        if rule is None:
            print(f"tpulint: unknown rule: {args.explain} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2
        scope = ("project" if isinstance(rule, analysis.ProjectRule)
                 else "file")
        print(f"{rule.name}  [{rule.severity}, {scope}-scoped]")
        print(f"  {rule.description}")
        doc = getattr(sys.modules.get(type(rule).__module__), "__doc__",
                      None)
        if doc:
            print()
            print(doc.strip())
        return 0

    if args.list_rules:
        counts = _load_counts(args.root or REPO_ROOT)
        for name in sorted(analysis.RULES):
            r = analysis.RULES[name]
            c = counts.get(name)
            tail = ""
            if c is not None:
                tail = f"  [last check: {c['open']} open"
                tail += (f", {c['baselined']} baselined]" if c["baselined"]
                         else "]")
            print(f"{name:22s} [{r.severity}] {r.description}{tail}")
        if counts:
            print("\n(counts from the last --check of this root; "
                  "re-run --check to refresh)")
        return 0

    targets = list(args.paths) + list(args.check)
    if args.changed is not None:
        scope = targets or ["paddle_tpu"]
        changed_root = (os.path.abspath(args.root) if args.root
                        else _resolve_root(scope))
        changed = _changed_files(changed_root, args.changed)
        if changed is None:
            print(f"tpulint: --changed {args.changed}: git unusable under "
                  f"{changed_root}", file=sys.stderr)
            return 2
        changed = [c for c in changed
                   if any(s in (".", "") or c == s
                          or c.startswith(s.rstrip("/") + "/")
                          for s in scope)]
        if not changed:
            print(f"tpulint: no changed files vs {args.changed} in scope "
                  f"({', '.join(scope)}) — nothing to lint")
            return 0
        targets = changed
    if not targets:
        targets = ["paddle_tpu"]
    root = os.path.abspath(args.root) if args.root else _resolve_root(targets)
    # a typo'd/missing target must be a usage error, not a clean exit —
    # otherwise a misconfigured CI job "passes" forever while linting nothing
    missing = [t for t in targets
               if not os.path.exists(t if os.path.isabs(t)
                                     else os.path.join(root, t))]
    if missing:
        print(f"tpulint: target(s) not found under {root}: "
              f"{', '.join(missing)}", file=sys.stderr)
        return 2

    unknown = [r for r in (args.select or []) + (args.ignore or [])
               if r not in analysis.RULES]
    if unknown:
        print(f"tpulint: unknown rule(s): {', '.join(unknown)} "
              f"(see --list-rules)", file=sys.stderr)
        return 2

    # Project rules (metrics-catalogue imports the live package + jax) run
    # on whole-package lints and explicit --select; a single-file spot-lint
    # stays a sub-second AST pass.
    abs_targets = [t if os.path.isabs(t) else os.path.join(root, t)
                   for t in targets]
    whole = {os.path.abspath(root),
             os.path.join(os.path.abspath(root), "paddle_tpu")}
    project_rules = (bool(args.select)
                     or any(os.path.abspath(t) in whole for t in abs_targets))

    select = set(args.select) if args.select else None
    ignore = set(args.ignore) if args.ignore else None
    if args.jobs > 1:
        findings = _run_parallel(analysis, root, targets, select, ignore,
                                 project_rules, args.jobs)
    else:
        findings = analysis.run_project(
            root, paths=targets, select=select, ignore=ignore,
            project_rules=project_rules)

    if args.write_baseline:
        entries = [{"rule": f.rule, "path": f.path, "content": f.content,
                    "justification": "TODO — one-line reason this finding "
                                     "is deliberate"}
                   for f in findings if f.severity != "note"]
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            json.dump(entries, fh, indent=2)
            fh.write("\n")
        print(f"tpulint: wrote {len(entries)} entries to "
              f"{args.write_baseline}; fill in every justification — the "
              f"loader rejects TODO stubs")
        return 0

    baselined, unused = [], []
    baseline_path = args.baseline or os.path.join(root, "tools",
                                                  "tpulint_baseline.json")
    if not args.no_baseline and os.path.exists(baseline_path):
        try:
            entries = analysis.load_baseline(baseline_path)
        except analysis.BaselineError as e:
            print(f"tpulint: {e}", file=sys.stderr)
            return 2
        findings, baselined, unused = analysis.apply_baseline(findings,
                                                              entries)
        # only entries whose rule ran AND whose path was linted can be
        # judged stale — a subdirectory spot-lint must not tell the
        # developer to delete justified entries elsewhere in the tree
        active = {n for n in analysis.RULES
                  if (not args.select or n in args.select)
                  and n not in (args.ignore or ())}
        rel_targets = [os.path.relpath(t, root).replace(os.sep, "/")
                       for t in abs_targets]

        def _in_scope(e):
            if e["rule"] not in active:
                return False
            rule = analysis.RULES.get(e["rule"])
            if isinstance(rule, analysis.ProjectRule):
                return project_rules
            return any(t in (".", "") or e["path"] == t
                       or e["path"].startswith(t.rstrip("/") + "/")
                       for t in rel_targets)

        unused = [e for e in unused if _in_scope(e)]

    if not args.select and not args.ignore:
        # full-catalogue runs refresh the --list-rules counts; a filtered
        # spot-lint must not make untouched rules look suddenly clean
        _save_counts(root, findings, baselined)

    if args.format == "json":
        print(analysis.render_json(findings, len(baselined), unused))
    else:
        print(analysis.render_text(findings, len(baselined), unused))

    fail_severities = (("error",) if args.fail_on == "error"
                       else ("error", "warning"))
    return 1 if any(f.severity in fail_severities for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
