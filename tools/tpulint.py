#!/usr/bin/env python
"""tpulint — TPU-native static-analysis driver (tier-1 gate).

Polices trace-safety, collectives, and dtype discipline across the package:
host syncs inside jitted steps, impure traces, mesh-axis typos, donated
buffers read after the call, f32 drift in bf16 paths, exported no-ops,
swallowed faults in recovery code, the metric-namespace catalogue, and docs
staleness.  Rule catalogue: README §Static analysis;
engine: ``paddle_tpu/analysis/``.

Usage::

    python tools/tpulint.py --check paddle_tpu          # the tier-1 gate
    python tools/tpulint.py --list-rules
    python tools/tpulint.py path/ --format json
    python tools/tpulint.py --check paddle_tpu --select impure-trace
    python tools/tpulint.py --check paddle_tpu --write-baseline /tmp/b.json

Exit codes: 0 clean, 1 findings at/above --fail-on, 2 usage/baseline error.

Suppress a single line with ``# tpulint: disable=rule-name`` (or ``=all``);
grandfather history in ``tools/tpulint_baseline.json`` — every entry MUST
carry a one-line justification or the driver refuses to run.

The engine is loaded by file path under a private module name so linting
works even when ``import paddle_tpu`` itself is broken — a linter that needs
the patient healthy is not a diagnostic tool.  (The metrics-catalogue rule
does import the live package, and degrades to a note if it cannot.)
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_analysis():
    """Load paddle_tpu/analysis as a standalone package (no paddle_tpu
    __init__, no jax import)."""
    name = "_tpulint_analysis"
    if name in sys.modules:
        return sys.modules[name]
    pkg_dir = os.path.join(REPO_ROOT, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _resolve_root(targets):
    """Repo root when every target lives inside it, else the CWD — lets the
    same driver lint fixture trees in tests."""
    abs_targets = [os.path.abspath(t) for t in targets]
    if all(t.startswith(REPO_ROOT + os.sep) or t == REPO_ROOT
           for t in abs_targets):
        return REPO_ROOT
    return os.getcwd()


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="tpulint", description=__doc__.splitlines()[0].strip())
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument("--check", action="append", default=[], metavar="PATH",
                    help="path to lint (alias for a positional path; the "
                         "tier-1 invocation is --check paddle_tpu)")
    ap.add_argument("--root", default=None,
                    help="project root for relative paths/baseline "
                         "(default: auto)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: <root>/tools/"
                         "tpulint_baseline.json when present)")
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--select", action="append", metavar="RULE",
                    help="run only these rules (repeatable)")
    ap.add_argument("--ignore", action="append", metavar="RULE",
                    help="skip these rules (repeatable)")
    ap.add_argument("--fail-on", choices=("error", "warning"),
                    default="warning",
                    help="lowest severity that fails the run (default: "
                         "warning; notes never fail)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write current findings as baseline entries (each "
                         "needs its justification filled in before the "
                         "loader will accept it)")
    args = ap.parse_args(argv)

    analysis = load_analysis()

    if args.list_rules:
        for name in sorted(analysis.RULES):
            r = analysis.RULES[name]
            print(f"{name:22s} [{r.severity}] {r.description}")
        return 0

    targets = list(args.paths) + list(args.check)
    if not targets:
        targets = ["paddle_tpu"]
    root = os.path.abspath(args.root) if args.root else _resolve_root(targets)
    # a typo'd/missing target must be a usage error, not a clean exit —
    # otherwise a misconfigured CI job "passes" forever while linting nothing
    missing = [t for t in targets
               if not os.path.exists(t if os.path.isabs(t)
                                     else os.path.join(root, t))]
    if missing:
        print(f"tpulint: target(s) not found under {root}: "
              f"{', '.join(missing)}", file=sys.stderr)
        return 2

    unknown = [r for r in (args.select or []) + (args.ignore or [])
               if r not in analysis.RULES]
    if unknown:
        print(f"tpulint: unknown rule(s): {', '.join(unknown)} "
              f"(see --list-rules)", file=sys.stderr)
        return 2

    # Project rules (metrics-catalogue imports the live package + jax) run
    # on whole-package lints and explicit --select; a single-file spot-lint
    # stays a sub-second AST pass.
    abs_targets = [t if os.path.isabs(t) else os.path.join(root, t)
                   for t in targets]
    whole = {os.path.abspath(root),
             os.path.join(os.path.abspath(root), "paddle_tpu")}
    project_rules = (bool(args.select)
                     or any(os.path.abspath(t) in whole for t in abs_targets))

    findings = analysis.run_project(
        root, paths=targets,
        select=set(args.select) if args.select else None,
        ignore=set(args.ignore) if args.ignore else None,
        project_rules=project_rules)

    if args.write_baseline:
        entries = [{"rule": f.rule, "path": f.path, "content": f.content,
                    "justification": "TODO — one-line reason this finding "
                                     "is deliberate"}
                   for f in findings if f.severity != "note"]
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            json.dump(entries, fh, indent=2)
            fh.write("\n")
        print(f"tpulint: wrote {len(entries)} entries to "
              f"{args.write_baseline}; fill in every justification — the "
              f"loader rejects TODO stubs")
        return 0

    baselined, unused = [], []
    baseline_path = args.baseline or os.path.join(root, "tools",
                                                  "tpulint_baseline.json")
    if not args.no_baseline and os.path.exists(baseline_path):
        try:
            entries = analysis.load_baseline(baseline_path)
        except analysis.BaselineError as e:
            print(f"tpulint: {e}", file=sys.stderr)
            return 2
        findings, baselined, unused = analysis.apply_baseline(findings,
                                                              entries)
        # only entries whose rule ran AND whose path was linted can be
        # judged stale — a subdirectory spot-lint must not tell the
        # developer to delete justified entries elsewhere in the tree
        active = {n for n in analysis.RULES
                  if (not args.select or n in args.select)
                  and n not in (args.ignore or ())}
        rel_targets = [os.path.relpath(t, root).replace(os.sep, "/")
                       for t in abs_targets]

        def _in_scope(e):
            if e["rule"] not in active:
                return False
            rule = analysis.RULES.get(e["rule"])
            if isinstance(rule, analysis.ProjectRule):
                return project_rules
            return any(t in (".", "") or e["path"] == t
                       or e["path"].startswith(t.rstrip("/") + "/")
                       for t in rel_targets)

        unused = [e for e in unused if _in_scope(e)]

    if args.format == "json":
        print(analysis.render_json(findings, len(baselined), unused))
    else:
        print(analysis.render_text(findings, len(baselined), unused))

    fail_severities = (("error",) if args.fail_on == "error"
                       else ("error", "warning"))
    return 1 if any(f.severity in fail_severities for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
