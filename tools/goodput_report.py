#!/usr/bin/env python
"""goodput_report — render a goodput ledger's wall-clock and token
attribution (paddle_tpu.observability.goodput as a CLI), and gate on it.

Live mode — scrape one telemetry endpoint's /metrics::

    python tools/goodput_report.py HOST:PORT [--threshold 0.5] [--json]

The `goodput_seconds_total{domain,bucket}` counters are re-aggregated
per domain into the bucket table (idle included — per domain the buckets
sum to the wall span, that is the ledger's conservation invariant), the
goodput ratio is derived as productive/wall from the same counters, and
`goodput_tokens_total{domain,class}` fills the token line.

Flight mode — read a flight-recorder dump instead of a live process::

    python tools/goodput_report.py --flight DUMP.jsonl [--threshold ...]
    python tools/goodput_report.py --flight DUMPDIR

Renders the LAST `goodput_ledger` event per domain from the dump (a
directory picks the newest `flight_*.jsonl` inside it) — the post-mortem
view of a run that already closed its ledger.

`--threshold R` turns the report into a gate: exit 2 when any reporting
domain's goodput ratio is below R.  Domains with no productive buckets
defined (fleet) never trip the gate.  Exit 1 means NO goodput data at
all — distinct from healthy, so a cron gate cannot rot silently when a
replica stops exporting the family.

`--selftest` runs the embedded corpus: a healthy and a degraded canned
exposition must produce the golden ratios and gate decisions, and a
canned flight dump must render.  Exit 0 = healthy.

Exit codes: 0 healthy report; 1 no goodput data; 2 `--threshold` tripped.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

__all__ = ["main", "build_report", "gate"]

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _plane():
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu.observability import goodput, scrape
    return goodput, scrape


# ------------------------------------------------------------------- build
def build_report(samples, productive_map):
    """Per-domain report rows from a SampleSet of goodput_* families:
    ``{domain: {"wall_s", "ratio", "buckets", "tokens"}}`` — ``ratio``
    is None for domains with no productive buckets defined (fleet:
    counter-only, no conservation, nothing to gate)."""
    domains = {}
    for labels, v in samples.match("goodput_seconds_total"):
        d, b = labels.get("domain"), labels.get("bucket")
        if d and b:
            row = domains.setdefault(d, {"buckets": {}, "tokens": {}})
            row["buckets"][b] = row["buckets"].get(b, 0.0) + v
    for labels, v in samples.match("goodput_tokens_total"):
        d, c = labels.get("domain"), labels.get("class")
        if d and c:
            row = domains.setdefault(d, {"buckets": {}, "tokens": {}})
            row["tokens"][c] = row["tokens"].get(c, 0) + int(v)
    for d, row in domains.items():
        wall = sum(row["buckets"].values())
        prod_buckets = productive_map.get(d, ())
        productive = sum(row["buckets"].get(b, 0.0) for b in prod_buckets)
        row["wall_s"] = round(wall, 6)
        row["ratio"] = (round(productive / wall, 6)
                        if prod_buckets and wall > 0 else None)
    return domains


def report_from_flight(path):
    """Last `goodput_ledger` event per domain out of a flight-recorder
    JSONL dump (a directory argument picks the newest flight_*.jsonl)."""
    if os.path.isdir(path):
        dumps = sorted(f for f in os.listdir(path)
                       if f.startswith("flight_") and f.endswith(".jsonl"))
        if not dumps:
            raise FileNotFoundError(f"no flight_*.jsonl under {path}")
        path = os.path.join(path, dumps[-1])
    domains = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                evt = json.loads(line)
            except ValueError:
                continue  # a torn tail line must not kill the post-mortem
            if evt.get("kind") != "goodput_ledger":
                continue
            d = evt.get("domain", "?")
            domains[d] = {  # later events win: last ledger close per domain
                "wall_s": evt.get("wall_s", 0.0),
                "ratio": evt.get("ratio"),
                "buckets": dict(evt.get("buckets") or {}),
                "tokens": dict(evt.get("tokens") or {}),
                "reason": evt.get("reason"),
            }
    return domains


# ------------------------------------------------------------------ render
def render_text(report, productive_map):
    lines = []
    for d in sorted(report):
        row = report[d]
        wall = row.get("wall_s") or sum(row["buckets"].values())
        ratio = row.get("ratio")
        head = f"domain {d}: wall {wall:.3f}s"
        if ratio is not None:
            head += f"  goodput {ratio * 100:.1f}%"
        if row.get("reason"):
            head += f"  (ledger close: {row['reason']})"
        lines.append(head)
        prod = set(productive_map.get(d, ()))
        width = max((len(b) for b in row["buckets"]), default=6)
        for b, v in sorted(row["buckets"].items(),
                           key=lambda kv: -kv[1]):
            share = v / wall * 100 if wall > 0 else 0.0
            star = "*" if b in prod else " "
            lines.append(f"  {b:<{width}}{star} {v:>10.3f}s  {share:5.1f}%")
        toks = {c: n for c, n in row["tokens"].items() if n}
        if toks:
            useful = toks.get("useful", 0)
            waste = sum(n for c, n in toks.items() if c != "useful")
            eff = useful / (useful + waste) if useful + waste else 0.0
            detail = " ".join(f"{c}={n}" for c, n in sorted(toks.items()))
            lines.append(f"  tokens: {detail}  "
                         f"(efficiency {eff * 100:.1f}%)")
        lines.append("")
    lines.append("(* = productive bucket: the goodput numerator)")
    return "\n".join(lines)


def gate(report, threshold):
    """(exit_code, [degraded domain names]) for a report under
    ``--threshold``: 1 = no data, 2 = a reporting domain is below the
    threshold, 0 = healthy.  ``threshold=None`` only distinguishes
    no-data from healthy."""
    if not report:
        return 1, []
    if threshold is None:
        return 0, []
    degraded = sorted(d for d, row in report.items()
                      if row.get("ratio") is not None
                      and row["ratio"] < threshold)
    return (2, degraded) if degraded else (0, [])


def run(report, productive_map, threshold, as_json):
    code, degraded = gate(report, threshold)
    if code == 1:
        print("goodput_report: no goodput_* data — nothing to attribute",
              file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps({"domains": report, "degraded": degraded},
                         default=repr, sort_keys=True))
    else:
        print(render_text(report, productive_map))
    for d in degraded:
        print(f"goodput_report: domain {d!r} goodput "
              f"{report[d]['ratio']:.4f} below threshold {threshold}",
              file=sys.stderr)
    return code


# ---------------------------------------------------------------- selftest
#: Healthy corpus: train 94% in step, serve 90% productive.
SELFTEST_HEALTHY = """\
# TYPE goodput_seconds_total counter
goodput_seconds_total{domain="train",bucket="step"} 94.0
goodput_seconds_total{domain="train",bucket="compile"} 3.0
goodput_seconds_total{domain="train",bucket="checkpoint_save"} 2.0
goodput_seconds_total{domain="train",bucket="idle"} 1.0
goodput_seconds_total{domain="serve",bucket="decode"} 80.0
goodput_seconds_total{domain="serve",bucket="prefill"} 8.0
goodput_seconds_total{domain="serve",bucket="verify"} 2.0
goodput_seconds_total{domain="serve",bucket="idle"} 10.0
# TYPE goodput_tokens_total counter
goodput_tokens_total{domain="serve",class="useful"} 9000
goodput_tokens_total{domain="serve",class="spec_rolled_back"} 100
"""

#: Degraded corpus: restarts and rollback waste eat the train clock.
SELFTEST_DEGRADED = """\
# TYPE goodput_seconds_total counter
goodput_seconds_total{domain="train",bucket="step"} 30.0
goodput_seconds_total{domain="train",bucket="restore"} 40.0
goodput_seconds_total{domain="train",bucket="restart_backoff"} 20.0
goodput_seconds_total{domain="train",bucket="idle"} 10.0
goodput_seconds_total{domain="fleet",bucket="respawn"} 55.0
"""

SELFTEST_FLIGHT = """\
{"flight_recorder":1,"reason":"selftest","events":2}
{"seq":1,"kind":"goodput_ledger","domain":"train","reason":"run_end",\
"wall_s":10.0,"ratio":0.9,"buckets":{"step":9.0,"idle":1.0},\
"tokens":{"useful":0}}
{"seq":2,"kind":"goodput_ledger","domain":"train","reason":"fatal",\
"wall_s":20.0,"ratio":0.45,"buckets":{"step":9.0,"restore":9.0,\
"idle":2.0},"tokens":{"useful":0}}
"""


def selftest():
    goodput, scrape = _plane()
    import tempfile

    def _report(corpus):
        ss = scrape.SampleSet().add_families(
            scrape.parse_prometheus(corpus))
        return build_report(ss, goodput.PRODUCTIVE)

    healthy = _report(SELFTEST_HEALTHY)
    assert healthy["train"]["ratio"] == 0.94, healthy["train"]
    assert healthy["serve"]["ratio"] == 0.9, healthy["serve"]
    assert healthy["train"]["wall_s"] == 100.0
    assert healthy["serve"]["tokens"]["useful"] == 9000
    assert gate(healthy, 0.5) == (0, [])
    assert gate(healthy, None) == (0, [])

    degraded = _report(SELFTEST_DEGRADED)
    assert degraded["train"]["ratio"] == 0.3, degraded["train"]
    # fleet has no productive buckets: reports, never gates
    assert degraded["fleet"]["ratio"] is None
    assert gate(degraded, 0.5) == (2, ["train"])
    assert gate({}, 0.5) == (1, [])  # absent family = no-data, not healthy

    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "flight_selftest_0001_00000002.jsonl")
        with open(p, "w") as f:
            f.write(SELFTEST_FLIGHT)
        for arg in (p, td):  # file and newest-in-directory forms
            fl = report_from_flight(arg)
            assert fl["train"]["ratio"] == 0.45, fl  # last event wins
            assert fl["train"]["reason"] == "fatal"
        assert gate(fl, 0.5) == (2, ["train"])

    text = render_text(healthy, goodput.PRODUCTIVE)
    assert "domain train" in text and "goodput 94.0%" in text
    assert "step" in text and "efficiency" in text
    print("goodput_report selftest: ok (healthy ratio 0.94, degraded "
          "gate trips at 0.5, flight last-event-wins)")
    return 0


# -------------------------------------------------------------------- main
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("target", nargs="?", metavar="HOST:PORT",
                    help="telemetry endpoint to scrape (/metrics)")
    ap.add_argument("--flight", metavar="DUMP",
                    help="render a flight-recorder dump (.jsonl or a "
                         "directory of them) instead of scraping")
    ap.add_argument("--threshold", type=float, default=None,
                    help="exit 2 when any domain's goodput ratio is "
                         "below this")
    ap.add_argument("--timeout", type=float, default=2.0)
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--selftest", action="store_true")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    goodput, scrape = _plane()
    if args.flight:
        try:
            report = report_from_flight(args.flight)
        except (OSError, FileNotFoundError) as e:
            print(f"goodput_report: {e}", file=sys.stderr)
            return 1
    elif args.target:
        import urllib.request
        url = (args.target if "//" in args.target
               else f"http://{args.target}")
        with urllib.request.urlopen(f"{url.rstrip('/')}/metrics",
                                    timeout=args.timeout) as resp:
            text = resp.read().decode("utf-8", "replace")
        ss = scrape.SampleSet().add_families(scrape.parse_prometheus(text))
        report = build_report(ss, goodput.PRODUCTIVE)
    else:
        ap.error("need HOST:PORT, --flight DUMP, or --selftest")
    return run(report, goodput.PRODUCTIVE, args.threshold, args.as_json)


if __name__ == "__main__":
    sys.exit(main())
