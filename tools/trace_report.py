#!/usr/bin/env python
"""trace_report — join per-op census costs with recorded span timings.

The ROADMAP's census<->timeline join: the cost model knows how much
compute/traffic each op SHOULD cost (``census.per_op_census`` /
``collective_census``), the timeline knows how long each span ACTUALLY
took (chrome-trace JSON from ``Profiler.export`` / the flight recorder's
``*.trace.json``, or the span events inside a flight-recorder JSONL dump).
This tool joins the two by name into a top-K per-op cost-attribution
table — the first thing to read when MFU drops: which op eats the time,
and whether its measured share matches its analytic share.

Inputs
------
--trace trace.json          chrome-trace document ({"traceEvents": [...]}
                            or a bare event list; complete 'X' events and
                            'B'/'E' pairs both count)
--flight dump.jsonl         alternative timing source: a flight-recorder
                            dump whose `span` events carry duration_s
--tracez trace.json         alternative timing source: a `/tracez` JSON
                            trace (one trace's span tree), a
                            `traces_*.json` store dump, or a list of
                            traces — per-op census attribution on a
                            SINGLE sampled request
--xplane dump               per-HLO DEVICE timings from a
                            `jax.profiler.trace()` dump: a `.xplane.pb`
                            file or any logdir above one
                            (observability.xplane — measured GF/s per
                            op instead of a span-name substring join)
--census census.json        per-op cost table: the per_op_census() list,
                            or a {name: {flops, bytes}} mapping, or a
                            collective_census() dict
--top K                     rows to print (default 20, by total time,
                            then by flops for time-less census rows)
--json out.json             also write the full joined table as JSON
--roofline                  residual-annotate joined rows against the
                            min-time roofline (observability.roofline):
                            predicted µs, measured/predicted ratio,
                            compute-/memory-bound; peaks default to the
                            cost_model lookups, overridable with
                            --peak-flops / --peak-bw

Join rule: exact name match first, else substring containment either way
(census op ``dot.4`` matches timeline event ``jit_step/dot.4``); census
rows without a timed event and events without census costs both stay in
the table (flagged) — unattributed time is a finding, not noise.

Exit code: 0 on a usable table; 1 when there is nothing to attribute at
all; 2 when a census was supplied but NOT ONE timed row joined it — CI
can gate on "the profile and the cost model describe the same program".
``--json`` writes ``{"schema_version": 2, "rows": [...]}``.

Usage::

    python tools/trace_report.py --trace prof/worker.json \
        --census per_op.json --top 15
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import OrderedDict

__all__ = ["load_timeline", "load_census", "join", "render_text", "main",
           "SCHEMA_VERSION"]

#: Version of the --json document ({"schema_version", "rows"}).  v1 was
#: the bare row list; v2 wrapped it so consumers can detect drift.
SCHEMA_VERSION = 2


# ------------------------------------------------------------------ loading
def load_timeline(path=None, events=None, flight_path=None,
                  tracez_path=None, xplane_path=None):
    """-> OrderedDict name -> {"count", "total_us"} aggregated timings."""
    if xplane_path is not None:
        return _timeline_from_xplane(xplane_path)
    if tracez_path is not None:
        events = _events_from_tracez(tracez_path)
    elif flight_path is not None:
        events = _events_from_flight(flight_path)
    elif path is not None:
        with open(path) as f:
            doc = json.load(f)
        events = doc.get("traceEvents", doc) if isinstance(doc, dict) \
            else doc
    out: "OrderedDict[str, dict]" = OrderedDict()
    open_begins: dict = {}
    for e in events or []:
        if not isinstance(e, dict):
            continue
        name, ph = e.get("name"), e.get("ph", "X")
        if name is None:
            continue
        if ph == "X" and "dur" in e:
            dur = float(e["dur"])
        elif ph == "B":
            open_begins.setdefault((e.get("tid", 0), name), []).append(
                float(e.get("ts", 0.0)))
            continue
        elif ph == "E":
            stack = open_begins.get((e.get("tid", 0), name))
            if not stack:
                continue
            dur = float(e.get("ts", 0.0)) - stack.pop()
        else:
            continue
        row = out.setdefault(name, {"count": 0, "total_us": 0.0})
        row["count"] += 1
        row["total_us"] += max(0.0, dur)
    return out


def _timeline_from_xplane(path):
    """Per-HLO device timings of a profiler dump, via the dependency-free
    observability.xplane reader (imported lazily: the other sources must
    keep working without the package on sys.path)."""
    import os
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu.observability import xplane
    return xplane.to_timeline(path)


def _events_from_flight(path):
    """Span-close events of a flight-recorder JSONL dump as chrome 'X'
    events (mirrors FlightRecorder.to_chrome_trace, but offline)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == "span" and "duration_s" in rec:
                events.append({"name": rec.get("name", "?"), "ph": "X",
                               "dur": float(rec["duration_s"]) * 1e6})
    return events


def _events_from_tracez(path):
    """Span tree(s) of a `/tracez` JSON document as chrome 'X' events.

    Accepts the three shapes the tracing plane writes: one trace dict
    (``/tracez?trace_id=...``), a store dump ``{"traces": [...]}``
    (``traces_<reason>_*.json`` next to a flight black box), or a bare
    list of trace dicts."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        traces = doc["traces"] if "traces" in doc else [doc]
    else:
        traces = doc
    events = []

    def walk(span):
        dur = span.get("duration_s")
        if dur is not None:
            events.append({"name": span.get("name", "?"), "ph": "X",
                           "dur": float(dur) * 1e6})
        for child in span.get("children", ()):
            walk(child)

    for t in traces:
        if not isinstance(t, dict):
            continue
        for s in t.get("spans", ()):
            walk(s)
    return events


def load_census(path):
    """-> OrderedDict name -> {"opcode", "flops", "bytes"}; accepts the
    three shapes documented in the module docstring."""
    with open(path) as f:
        doc = json.load(f)
    out: "OrderedDict[str, dict]" = OrderedDict()
    if isinstance(doc, list):  # per_op_census() rows
        for row in doc:
            name = str(row.get("name", "?"))
            prev = out.setdefault(name, {"opcode": row.get("opcode", ""),
                                         "flops": 0.0, "bytes": 0.0})
            prev["flops"] += float(row.get("flops", 0) or 0)
            prev["bytes"] += float(row.get("bytes_out", 0) or 0) \
                + float(row.get("bytes_in", 0) or 0) \
                + float(row.get("bytes", 0) or 0)
        return out
    if isinstance(doc, dict) and "counts" in doc:  # collective_census()
        for key, op in (("bytes_allreduce", "all-reduce"),
                        ("bytes_allgather", "all-gather"),
                        ("bytes_reducescatter", "reduce-scatter"),
                        ("bytes_ppermute", "collective-permute"),
                        ("bytes_alltoall", "all-to-all")):
            if doc.get(key):
                out[op] = {"opcode": op, "flops": 0.0,
                           "bytes": float(doc[key])}
        return out
    if isinstance(doc, dict):  # {name: {flops, bytes}}
        for name, row in doc.items():
            out[str(name)] = {"opcode": str(row.get("opcode", "")),
                              "flops": float(row.get("flops", 0) or 0),
                              "bytes": float(row.get("bytes", 0) or 0)}
        return out
    raise ValueError(f"unrecognized census document shape in {path}")


# ------------------------------------------------------------------ joining
def _roofline():
    """The roofline plane, imported lazily with the same sys.path dance as
    `_timeline_from_xplane` (stdlib-only module, so this stays cheap)."""
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from paddle_tpu.observability import roofline
    return roofline


def _match(event_name, census):
    # the join rule lives in roofline.match_name (one matcher for the CLI
    # and the residual plane); inline fallback keeps this tool usable as a
    # bare script with the package unreachable
    try:
        return _roofline().match_name(event_name, census)
    except ImportError:
        pass
    if event_name in census:
        return event_name
    # trace names prefix ops with the program path ("jit_step/dot.12"):
    # try the trailing component exactly before any fuzzy containment
    tail = event_name.rsplit("/", 1)[-1]
    if tail in census:
        return tail
    # fuzzy fallback: LONGEST containment wins, so census row "dot.12"
    # beats "dot" / "dot.1" for event ".../dot.12"
    best = None
    for cname in census:
        if (cname in event_name or event_name in cname) \
                and (best is None or len(cname) > len(best)):
            best = cname
    return best


def join(timeline, census):
    """-> list of rows {name, count, total_us, flops, bytes, opcode,
    gflops_per_s, matched} sorted by total time desc, then flops desc.
    Census ops no event timed keep total_us=0 (matched=False) so missing
    attribution is visible."""
    rows, used = [], set()
    for name, t in timeline.items():
        cname = _match(name, census)
        c = census.get(cname) if cname else None
        if cname:
            used.add(cname)
        secs = t["total_us"] / 1e6
        rows.append({
            "name": name, "count": t["count"],
            "total_us": round(t["total_us"], 3),
            "opcode": (c or {}).get("opcode", ""),
            "flops": (c or {}).get("flops", 0.0),
            "bytes": (c or {}).get("bytes", 0.0),
            "gflops_per_s": round((c["flops"] / secs) / 1e9, 3)
            if c and c["flops"] and secs > 0 else 0.0,
            "matched": c is not None,
        })
    for cname, c in census.items():
        if cname in used:
            continue
        rows.append({"name": cname, "count": 0, "total_us": 0.0,
                     "opcode": c.get("opcode", ""), "flops": c["flops"],
                     "bytes": c["bytes"], "gflops_per_s": 0.0,
                     "matched": False})
    rows.sort(key=lambda r: (-r["total_us"], -r["flops"], -r["bytes"],
                             r["name"]))
    return rows


# ---------------------------------------------------------------- rendering
def _human(n, unit=""):
    for div, suf in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(n) >= div:
            return f"{n / div:.2f}{suf}{unit}"
    return f"{n:.0f}{unit}"


def render_text(rows, top=20):
    total_us = sum(r["total_us"] for r in rows) or 1.0
    head = (f"{'op':40s} {'count':>6s} {'time_ms':>10s} {'time%':>6s} "
            f"{'flops':>9s} {'bytes':>9s} {'GF/s':>8s}")
    lines = [head, "-" * len(head)]
    for r in rows[:top]:
        mark = "" if r["matched"] or r["total_us"] == 0 else " *"
        lines.append(
            f"{(r['name'] + mark)[:40]:40s} {r['count']:6d} "
            f"{r['total_us'] / 1e3:10.3f} "
            f"{100.0 * r['total_us'] / total_us:6.1f} "
            f"{_human(r['flops']):>9s} {_human(r['bytes']):>9s} "
            f"{r['gflops_per_s']:8.2f}")
    shown = min(top, len(rows))
    lines.append(f"({shown}/{len(rows)} ops shown; * = no census match; "
                 f"time-less rows are census ops never seen on the "
                 f"timeline)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--trace", help="chrome-trace JSON (Profiler.export)")
    src.add_argument("--flight",
                     help="flight-recorder JSONL dump (span events)")
    src.add_argument("--tracez",
                     help="/tracez JSON trace or traces_*.json store dump "
                          "(per-request span tree)")
    src.add_argument("--xplane",
                     help="jax.profiler .xplane.pb dump (or a logdir "
                          "above one): per-HLO device timings")
    ap.add_argument("--census", default=None,
                    help="per-op census JSON (per_op_census / "
                         "collective_census output)")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the full joined table as JSON here")
    ap.add_argument("--roofline", action="store_true",
                    help="residual-annotate the joined rows (predicted "
                         "min-time, measured/predicted ratio, compute- vs "
                         "memory-bound) and print the residual table")
    ap.add_argument("--peak-flops", type=float, default=None,
                    help="roofline FLOP/s denominator (default: "
                         "cost_model.peak_flops_per_device)")
    ap.add_argument("--peak-bw", type=float, default=None,
                    help="roofline HBM bytes/s denominator (default: "
                         "cost_model.peak_hbm_bytes_per_sec)")
    args = ap.parse_args(argv)

    timeline = load_timeline(path=args.trace, flight_path=args.flight,
                             tracez_path=args.tracez,
                             xplane_path=args.xplane)
    census = load_census(args.census) if args.census else OrderedDict()
    rows = join(timeline, census)
    if not rows:
        print("trace_report: no timed events and no census ops — nothing "
              "to attribute")
        return 1
    print(render_text(rows, top=args.top))
    if args.roofline:
        roofline = _roofline()
        pf, pbw = args.peak_flops, args.peak_bw
        if pf is None or pbw is None:
            from paddle_tpu import cost_model
            pf = cost_model.peak_flops_per_device() if pf is None else pf
            pbw = cost_model.peak_hbm_bytes_per_sec() if pbw is None \
                else pbw
        roofline.annotate_rows(rows, pf, pbw)
        print()
        print(roofline.render_text(
            sorted(rows, key=lambda r: (-r["wasted_us"], -r["total_us"],
                                        r["name"])), top=args.top))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump({"schema_version": SCHEMA_VERSION, "rows": rows},
                      f, indent=1)
        print(f"wrote {len(rows)} rows to {args.json_out}")
    if census and not any(r["matched"] and r["total_us"] > 0
                          for r in rows):
        # a census that joins NOTHING timed means the profile and the
        # cost model describe different programs — fail loudly so CI
        # can gate on it, and show WHAT failed to match so the operator
        # can tell a naming-scheme drift from an empty dump
        print("trace_report: census joined zero timed rows — the "
              "timeline and the census do not describe the same program",
              file=sys.stderr)
        timed = sorted((r for r in rows if r["total_us"] > 0),
                       key=lambda r: -r["total_us"])
        costed = sorted((r for r in rows if r["total_us"] == 0
                         and not r["matched"]),
                        key=lambda r: (-r["flops"], -r["bytes"]))
        for label, side in (("timeline", timed), ("census", costed)):
            names = ", ".join(r["name"] for r in side[:5]) or "(empty)"
            print(f"  unmatched {label} names (top {min(5, len(side))}): "
                  f"{names}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
