"""v5e-256 pod projection from measured single-chip rates + validated
collective-traffic formulas.

The attached hardware is ONE v5e chip; the pod-scale north star
(BASELINE.md:22: >=70% MFU ERNIE-3.0 pretrain on v5e-256) can only be
addressed analytically.  Method:

1. ANALYTIC per-step collective bytes for each parallel axis (the same
   formulas Megatron/GSPMD cost models use).
2. VALIDATION: the same shapes are compiled on the 8-device virtual CPU
   mesh and the optimized HLO's actual collective bytes are counted
   (distributed/census.py); the formula must agree before it is trusted at
   256 chips (--validate).
3. PROJECTION: step time at v5e-256 = measured single-chip compute time
   (from BENCH_r*.json rates) + exposed collective time on public ICI
   specs, reported as both a no-overlap lower bound and a full-overlap
   upper bound.  Writes PROJECTION.md (--write).

Public v5e numbers used (Google Cloud TPU docs / jax-ml scaling book):
  - 197 TF/s bf16 per chip
  - ICI: 4 links/chip, ~45 GB/s one-way per link, 2D torus (16x16 at 256)
  - DCN only between slices (not needed <=256)
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

ICI_LINK_GBS = 45.0          # one-way per link, v5e
RING_AXIS_GBS = 2 * ICI_LINK_GBS   # bidirectional ring on one torus axis
PEAK_TFS = 197.0


# ---------------------------------------------------------------- formulas

def ring_allreduce_s(bytes_, n, axis_gbs=RING_AXIS_GBS):
    """Ring allreduce wall time over n chips on one torus axis."""
    if n <= 1 or bytes_ == 0:
        return 0.0
    return 2 * bytes_ * (n - 1) / n / (axis_gbs * 1e9)


def ring_reduce_scatter_s(bytes_, n, axis_gbs=RING_AXIS_GBS):
    if n <= 1 or bytes_ == 0:
        return 0.0
    return bytes_ * (n - 1) / n / (axis_gbs * 1e9)


ring_all_gather_s = ring_reduce_scatter_s


def torus_allreduce_s(bytes_, n):
    """2-phase allreduce on a 2D torus (16x16 for 256): reduce-scatter+
    allgather along x, then allreduce of the 1/nx shard along y."""
    import math

    nx = int(math.sqrt(n))
    if nx * nx != n or nx <= 1:
        return ring_allreduce_s(bytes_, n)
    t1 = ring_reduce_scatter_s(bytes_, nx) + ring_all_gather_s(bytes_, nx)
    t2 = ring_allreduce_s(bytes_ / nx, nx)
    return t1 + t2


# ------------------------------------------------- per-config traffic models

def dp_step_bytes(n_params, grad_bytes=2):
    """Pure data parallelism: ONE gradient allreduce per step (bf16)."""
    return {"allreduce": n_params * grad_bytes}


def tp_layer_bytes(batch, seq, hidden, act_bytes=2):
    """Megatron TP: per decoder layer, fwd 2 allreduces of the activations
    (attention out + mlp out) and bwd 2 more (ref mp_layers.py:95,171 —
    ColumnParallel f/RowParallel g operators)."""
    a = batch * seq * hidden * act_bytes
    return {"allreduce_per_layer": 4 * a}


def pp_microbatch_bytes(micro_batch, seq, hidden, act_bytes=2):
    """1F1B: one activation send fwd + one grad send bwd per microbatch per
    stage boundary (ppermute pairs)."""
    return {"ppermute_per_micro": 2 * micro_batch * seq * hidden * act_bytes}


def zero2_step_bytes(n_params_shard_group, grad_bytes=2, param_bytes=2):
    """ZeRO-2 over the dp axis: reduce-scatter grads + allgather updated
    params once per step (ref sharded_train_step.py)."""
    return {"reducescatter": n_params_shard_group * grad_bytes,
            "allgather": n_params_shard_group * param_bytes}


# --------------------------------------------------------------- projections

def project_ernie_dp256(bench):
    """Config #4 at pod scale: BERT/ERNIE-base pure DP over 256 chips."""
    n_params = bench.get("ernie_n_params", 125e6)
    tok_s = bench.get("ernie_tokens_per_sec_per_chip")
    mfu_chip = bench.get("ernie_mfu")
    if not tok_s:
        return None
    batch, seq = bench.get("ernie_batch_seq", [512, 128])
    t_compute = batch * seq / tok_s
    g = dp_step_bytes(int(n_params))["allreduce"]
    t_comm = torus_allreduce_s(g, 256)
    return {
        "config": "ERNIE/BERT-base MLM pretrain, DP=256 (v5e-256)",
        "per_chip_batch": batch, "seq": seq,
        "global_batch": batch * 256,
        "measured_chip_step_s": round(t_compute, 4),
        "allreduce_bytes_per_step": g,
        "ici_allreduce_s": round(t_comm, 4),
        "step_s_no_overlap": round(t_compute + t_comm, 4),
        "step_s_full_overlap": round(max(t_compute, t_comm), 4),
        "mfu_chip_measured": mfu_chip,
        "mfu_pod_no_overlap": round(mfu_chip * t_compute / (t_compute + t_comm), 4),
        "mfu_pod_full_overlap": round(mfu_chip * t_compute / max(t_compute, t_comm), 4),
        "tokens_per_sec_pod_no_overlap": round(batch * seq * 256 / (t_compute + t_comm), 0),
    }


def project_llama7b_hybrid256(bench, tp_cal=1.0):
    """Config #5 at pod scale: LLaMA-2-7B, tp=4 x pp=8 x dp(zero2)=8.
    tp_cal: measured census/formula calibration multiplier on the tp
    allreduce traffic (GSPMD moves embedding/logit terms beyond the
    Megatron-minimal per-layer count)."""
    tp, pp, dp = 4, 8, 8
    n_layers, hidden, seq = 32, 4096, 2048
    n_params = 6.74e9
    micro, n_micro = 1, 64  # dp-local batch 64 -> global 512; bubble 11%
    # per-chip compute rate: take the measured h=4096 single-chip MFU (the
    # same kernels/fusions run inside the tp/pp shard), fall back to 738M
    mfu_chip = bench.get("llama_h4096_mfu") or bench.get("llama_mfu", 0.6)
    chip_tfs = mfu_chip * PEAK_TFS
    tokens_local = micro * n_micro * seq
    flops_local = 6 * (n_params / (tp * pp)) * tokens_local \
        + 3 * 2 * micro * n_micro * seq * seq * hidden * (n_layers // pp)
    t_compute = flops_local / (chip_tfs * 1e12)
    # TP allreduces: per layer per microbatch, over the tp=4 ring (one axis),
    # scaled by the measured census/formula calibration
    tpb = tp_layer_bytes(micro, seq, hidden)["allreduce_per_layer"] * tp_cal
    t_tp = (n_layers // pp) * n_micro * ring_allreduce_s(tpb, tp)
    # PP: 2 boundary transfers per microbatch (one fwd, one bwd), pipeline
    # bubble (pp-1)/n_micro of the compute
    ppb = pp_microbatch_bytes(micro, seq, hidden)["ppermute_per_micro"]
    t_pp = n_micro * ppb / (ICI_LINK_GBS * 1e9)
    bubble = (pp - 1) / n_micro
    # ZeRO-2 over dp=8: reduce-scatter + allgather of this stage's params
    z = zero2_step_bytes(int(n_params / (tp * pp)))
    t_dp = ring_reduce_scatter_s(z["reducescatter"], dp) \
        + ring_all_gather_s(z["allgather"], dp)
    t_comm = t_tp + t_pp + t_dp
    t_no = t_compute * (1 + bubble) + t_comm
    t_full = max(t_compute * (1 + bubble), t_comm)
    flops_global = 6 * n_params * tokens_local * dp \
        + 3 * 2 * micro * n_micro * dp * seq * seq * hidden * n_layers
    return {
        "config": "LLaMA-2-7B, tp=4 x pp=8 x dp(zero2)=8 (v5e-256)",
        "microbatch": micro, "n_microbatch": n_micro,
        "global_batch": micro * n_micro * dp,
        "chip_tfs_assumed": round(chip_tfs, 1),
        "mfu_chip_measured": mfu_chip,
        "t_compute_s": round(t_compute, 4),
        "pipeline_bubble_frac": round(bubble, 4),
        "t_tp_allreduce_s": round(t_tp, 4),
        "t_pp_ppermute_s": round(t_pp, 4),
        "t_zero2_s": round(t_dp, 4),
        "step_s_no_overlap": round(t_no, 4),
        "step_s_full_overlap": round(t_full, 4),
        "mfu_pod_no_overlap": round(
            flops_global / (t_no * 256 * PEAK_TFS * 1e12), 4),
        "mfu_pod_full_overlap": round(
            flops_global / (t_full * 256 * PEAK_TFS * 1e12), 4),
    }


def project_serving_capacity(bench):
    """Serving-capacity axis (inference/llm_server.py): per-chip decode
    rates and kv-cache capacity from the newest bench round, plus the paged
    layout's capacity at the same HBM budget and the PREFIX-CACHE capacity
    on the shared-prefix fleet trace.  Paged/prefix numbers come from the
    round's kv_paged_* / kv_prefix_* / kv_tier_* fields when present; until
    a round measures them, they are derived with the same trace accounting
    bench.py uses (mixed lengths 100..L step 100 for paged; one shared
    system prompt + varied tails for prefix, page_size 128; host DRAM ~10x
    HBM for the hierarchical kv tiers) and labeled so."""
    from bench import paged_capacity_trace, shared_prefix_trace

    tok8 = bench.get("llama_decode_steady_tokens_per_sec")
    dense_b = bench.get("kv_bf16_max_batch")
    if not tok8 or not dense_b:
        return None
    L_ctx = bench.get("llama_decode_prompt_len", 1024) + 128
    L_pad = ((L_ctx + 127) // 128) * 128
    _, pages_mean = paged_capacity_trace(L_pad, 128)
    gain = L_pad / (pages_mean * 128)
    dense_b8 = bench.get("kv_int8_max_batch")
    measured = "kv_paged_max_batch" in bench
    paged_b = bench.get("kv_paged_max_batch", int(dense_b * gain))
    paged_b8 = bench.get("kv_paged_int8_max_batch",
                         int((dense_b8 or 0) * gain))
    # prefix cache on the shared-prefix trace: the SAME page budget the
    # paged numbers used (budget_pages ~= paged_b * mixed-trace pages/req),
    # charged only for each request's unique pages
    tr = shared_prefix_trace(L_pad, 128)
    measured_px = "kv_prefix_max_batch" in bench
    budget_pages = paged_b * pages_mean
    prefix_b = bench.get("kv_prefix_max_batch", int(
        (budget_pages - tr["shared_full_pages"]) // tr["unique_pages"]))
    prefix_b8 = bench.get("kv_prefix_int8_max_batch", int(
        (paged_b8 * pages_mean - tr["shared_full_pages"])
        // tr["unique_pages"]) if paged_b8 else None)
    tok32q = bench.get("llama_decode_int8_b32_steady_tokens_per_sec")
    out = {
        "config": f"LLM decode service, 738M model @ ctx {L_pad} "
                  "(per chip; x256 for the pod)",
        "decode_tokens_per_sec_chip_b8": tok8,
        "decode_tokens_per_sec_chip_b32": bench.get(
            "llama_decode_b32_steady_tokens_per_sec"),
        "decode_tokens_per_sec_chip_b32_int8": tok32q,
        "kv_dense_bf16_max_batch": dense_b,
        "kv_dense_int8_max_batch": dense_b8,
        "kv_paged_max_batch": paged_b,
        "kv_paged_int8_max_batch": paged_b8,
        "paged_capacity_gain_mixed_trace": round(gain, 2),
        "paged_numbers_source": "measured (bench kv_paged_*)" if measured
        else "derived from dense round via the bench.py trace formula",
        "kv_prefix_max_batch": prefix_b,
        "kv_prefix_int8_max_batch": prefix_b8,
        "prefix_capacity_gain_vs_paged": round(
            prefix_b / max(paged_b, 1), 2),
        "prefix_trace_hit_ratio": bench.get(
            "llm_prefix_cache_hit_ratio", tr["hit_ratio"]),
        "prefix_trace": {k: tr[k] for k in
                         ("shared_len", "tail_len", "new_tokens",
                          "total_pages", "unique_pages", "n_requests")},
        "prefix_numbers_source": "measured (bench kv_prefix_*)"
        if measured_px
        else "derived from the paged numbers via the bench.py shared-prefix"
             " trace formula",
    }
    # hierarchical kv tiers (host RAM + disk under the prefix cache): warm
    # prefixes survive HBM eviction in a host pool and re-enter via one
    # batched upload, so the WARM-SET capacity scales with host DRAM while
    # decode throughput is untouched (demotion runs off the tick path).
    # A v5e-class host hangs ~10x its per-chip HBM in DRAM off each chip,
    # so the derived fallback multiplies the HBM prefix budget by 11 (HBM
    # + 10x host); a measured round's kv_tier_* fields replace it.
    measured_tier = "kv_tier_capacity_multiplier" in bench
    dram_to_hbm = 10
    tier_mult = bench.get("kv_tier_capacity_multiplier", 1 + dram_to_hbm)
    out.update({
        "kv_tier_capacity_multiplier": tier_mult,
        "kv_tier_warm_prefix_pages": int(budget_pages * tier_mult),
        "kv_tier_warm_prefix_batch": int(
            (budget_pages * tier_mult - tr["shared_full_pages"])
            // tr["unique_pages"]),
        "kv_promote_us_per_page": bench.get("kv_promote_us_per_page"),
        "kv_promote_vs_reprefill_ratio": bench.get(
            "kv_promote_vs_reprefill_ratio"),
        "tier_numbers_source": "measured (bench kv_tier_*)" if measured_tier
        else f"derived: host DRAM ~{dram_to_hbm}x per-chip HBM, promotion "
             "latency unmeasured until a round runs _bench_kv_tiers",
    })
    if tok32q:
        out["pod_decode_tokens_per_sec_256chips_int8_b32"] = round(
            tok32q * 256, 0)
    return out


# --------------------------------------------------------------- validation

def validate_on_cpu_mesh():
    """Compile small-shape steps on the 8-device virtual mesh and compare
    the census-counted collective bytes against the SAME formulas used for
    the 256-chip projection.  Returns a list of {case, formula, census,
    ratio} dicts."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.census import collective_census

    results = []

    # case 1: pure DP=8 — grad allreduce bytes == n_params * 4 (f32 grads
    # on CPU mesh; the formula's grad_bytes parameter)
    import paddle_tpu.nn as nn

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(64, 128), nn.Tanh(), nn.Linear(128, 8))
    n_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    mesh = dist.build_mesh(dp=8)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    mse = lambda x, y: paddle.mean((net(x) - y) ** 2)  # noqa: E731
    step = dist.ShardedTrainStep(net, mse, opt, mesh, zero_stage=0)
    x = paddle.to_tensor(np.random.randn(16, 64).astype(np.float32))
    y = paddle.to_tensor(np.random.randn(16, 8).astype(np.float32))
    step(x, y)
    census = step.compiled_stats(x, y)
    formula = dp_step_bytes(n_params, grad_bytes=4)["allreduce"]
    got = census["bytes_allreduce"]
    results.append({"case": "dp8_grad_allreduce", "formula": formula,
                    "census": got,
                    "ratio": round(got / max(formula, 1), 3)})

    # case 2+3: tp=2 Megatron decoder — the analytic model counts the 4
    # activation allreduces per layer; the GSPMD-partitioned step also moves
    # embedding/logit/loss terms, so the census exceeds the per-layer
    # formula.  Two sizes show the ratio converging toward the layer term as
    # layers/hidden grow; the LARGER config's ratio is exported as the
    # calibration multiplier the 7B projection applies to its tp traffic.
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    for tag, (h, inter, nl, vocab, B, S) in (
            ("tp2_tiny(h64,L2)", (64, 172, 2, 256, 8, 32)),
            ("tp2_mid(h256,L6)", (256, 688, 6, 512, 8, 64))):
        cfg = LlamaConfig(vocab_size=vocab, hidden_size=h,
                          intermediate_size=inter, num_hidden_layers=nl,
                          num_attention_heads=4, num_key_value_heads=4,
                          max_position_embeddings=S,
                          tensor_parallel=True, use_flash_attention=False)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        # mp-ONLY mesh (2 devices): isolates the tensor-parallel traffic —
        # with a dp axis present the census is dominated by the dp gradient
        # allreduce, which the projection models separately (zero2 terms)
        import jax as _jax

        mesh2 = dist.build_mesh(mp=2, devices=_jax.devices()[:2])
        opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                    parameters=model.parameters())

        def lm_loss(ids, labels, model=model):
            loss, _ = model(ids, labels=labels)
            return loss

        step2 = dist.ShardedTrainStep(model, lm_loss, opt2, mesh2,
                                      zero_stage=0)
        ids = paddle.to_tensor(np.random.randint(0, vocab, (B, S), np.int32))
        step2(ids, ids)
        census2 = step2.compiled_stats(ids, ids)
        formula2 = nl * tp_layer_bytes(B, S, h,
                                       act_bytes=4)["allreduce_per_layer"]
        got2 = census2["bytes_allreduce"]
        results.append({"case": f"{tag}_allreduce(layer-term formula)",
                        "formula": formula2, "census": got2,
                        "ratio": round(got2 / max(formula2, 1), 3)})
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--validate", action="store_true",
                    help="compile on the 8-device CPU mesh and compare the "
                         "census against the formulas")
    ap.add_argument("--write", action="store_true", help="write PROJECTION.md")
    args = ap.parse_args()

    if args.validate:
        # the axon TPU plugin force-appends itself to jax_platforms, so the
        # env var alone is not enough — pin the virtual CPU mesh in-process
        os.environ.setdefault("XLA_FLAGS",
                              "--xla_force_host_platform_device_count=8")
        import jax

        jax.config.update("jax_platforms", "cpu")

    paths = sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json")))
    bench = {}
    if paths:
        with open(paths[-1]) as f:
            bench = json.load(f)
        bench = bench.get("parsed", bench)

    val = validate_on_cpu_mesh() if args.validate else None
    tp_cal = val[-1]["ratio"] if val else 1.0
    proj = {
        "ici_model": {"link_gbs_oneway": ICI_LINK_GBS,
                      "ring_axis_gbs": RING_AXIS_GBS,
                      "topology": "2D torus 16x16 (v5e-256)"},
        "tp_traffic_calibration": tp_cal,
        "ernie_dp256": project_ernie_dp256(bench),
        "llama7b_hybrid256": project_llama7b_hybrid256(bench, tp_cal=tp_cal),
        "serving_capacity": project_serving_capacity(bench),
        "validation": val,
        "bench_source": os.path.basename(paths[-1]) if paths else None,
        "roofline_source": _newest_roofline(),
    }
    print(json.dumps(proj, indent=1))
    if args.write:
        write_md(proj)
    return proj


def _newest_roofline():
    """Basename of the newest roofline residual round, or None (same
    lexical 'newest = last glob match' contract as the BENCH source;
    tools/docs_lint.py polices that PROJECTION.md cites it)."""
    paths = sorted(glob.glob(os.path.join(ROOT, "ROOFLINE_*.json")))
    return os.path.basename(paths[-1]) if paths else None


def write_md(proj):
    lines = ["# PROJECTION — v5e-256 pod-scale estimates",
             "",
             "Generated by `python tools/project_pod.py --validate --write`.",
             "Single-chip rates are MEASURED (from "
             f"`{proj['bench_source']}`); collective times are analytic on "
             "public v5e ICI specs; the traffic formulas are validated "
             "against the 8-device virtual mesh census below.",
             ""]
    if proj.get("roofline_source"):
        lines += [f"Per-op measured-vs-predicted attribution: "
                  f"`{proj['roofline_source']}` (the roofline residual "
                  f"plane's newest round; see `tools/roofline_report.py "
                  f"--diff` for the regression sentinel).",
                  ""]
    lines += [
             "## Interconnect model", "",
             f"- ICI one-way per link: {ICI_LINK_GBS} GB/s; bidirectional "
             f"ring per torus axis: {RING_AXIS_GBS} GB/s",
             "- v5e-256 topology: 2D torus 16x16; allreduce = 2-phase "
             "(reduce-scatter+allgather along x, allreduce shard along y)",
             ""]
    for key, title in (("ernie_dp256", "ERNIE/BERT-base DP-256 (north star)"),
                       ("llama7b_hybrid256", "LLaMA-2-7B tp4 x pp8 x zero2-dp8"),
                       ("serving_capacity",
                        "Serving capacity (paged kv cache)")):
        p = proj.get(key)
        if not p:
            continue
        lines += [f"## {title}", ""]
        for k, v in p.items():
            lines.append(f"- {k}: {v}")
        lines.append("")
    if proj.get("validation"):
        lines += ["## Formula validation (8-device virtual mesh census)", "",
                  "| case | formula bytes | census bytes | ratio |",
                  "|---|---|---|---|"]
        for r in proj["validation"]:
            lines.append(f"| {r['case']} | {r['formula']} | {r['census']} "
                         f"| {r['ratio']} |")
        lines.append("")
    with open(os.path.join(ROOT, "PROJECTION.md"), "w") as f:
        f.write("\n".join(lines))
    print("wrote PROJECTION.md")


if __name__ == "__main__":
    main()
