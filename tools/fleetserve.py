#!/usr/bin/env python
"""fleetserve: N in-process LLM replicas behind the prefix-affinity router
— the serving plane's operator CLI (README §Serving, "Multi-replica
router").

Usage::

    python tools/fleetserve.py [--replicas 2] [--port 0]
        [--page-size 16] [--slots 2] [--max-seq-len 128]
        [--affinity-blocks 4] [--controller-interval 5.0]
        [--iterations N]
    python tools/fleetserve.py --selftest
    python tools/fleetserve.py --procs [--model tiny|stub]
        [--drain-deadline 5.0]
    python tools/fleetserve.py --procs --selftest

Starts ``--replicas`` tiny-model ``LLMEngine`` replicas (each on its own
ephemeral telemetry+data port), wires a ``Router`` over them (its own
`/metrics`, `/healthz`, `/routerz` on ``--port``), and runs a
``FleetController`` loop: every ``--controller-interval`` seconds it
scrapes the fleet, evaluates the alert rules, restarts/quarantines sick
replicas, and logs scale signals.  ``--iterations`` bounds the loop for
scripting (0 = run until interrupted).  Point
``tools/fleetwatch.py --routerz HOST:PORT`` at the router address it
prints.

The tiny Llama keeps this runnable on a laptop CPU; production fleets
replace the in-process replicas with real engine processes and pass
``(name, "host:port")`` pairs to ``Router`` — everything else (affinity,
drain, retry-safety, controller) is identical.

``--procs`` IS that production shape, locally: a ``ReplicaSupervisor``
spawns each replica as a real ``python -m
paddle_tpu.inference.replica_main`` subprocess (its own interpreter, its
own telemetry port), gates rotation entry on ``/healthz``, restarts
crashed children with jittered exponential backoff, quarantines
flappers, and actuates the controller's scale signals by actually
spawning/reaping processes.  The supervisor also serves ``/procz`` on
the router port and acts as the router's death witness, so a replica
dying mid-request is retried on a sibling with zero double-delivery.
``--model stub`` swaps the tiny Llama for a deterministic no-JAX token
oracle — same wire protocol, seconds-fast spawns — for drills and CI.

``--selftest`` runs a deterministic smoke: 2 replicas, a shared-prefix
trace routed through the live wire path, asserting affinity convergence
(same-prefix requests on ONE replica), exact token parity with the
engine run solo, drain shifting traffic with zero loss, and a routerz
document fleetwatch can render.  Exit 0 = the serving plane works here.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_fleet(n_replicas, page_size, slots, max_seq_len, router_port,
                 affinity_blocks, seed=7):
    """(router, [ReplicaServer], FleetController) over tiny-Llama engines."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.llm_server import LLMEngine
    from paddle_tpu.inference.router import (
        FleetController, ReplicaServer, Router,
    )
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(seed)
    cfg = LlamaConfig.tiny(tensor_parallel=False, use_flash_attention=False,
                           max_position_embeddings=max(256, max_seq_len))
    model = LlamaForCausalLM(cfg)
    model.eval()
    replicas = []
    for i in range(n_replicas):
        eng = LLMEngine(model, max_batch_slots=slots,
                        max_seq_len=max_seq_len, kv_layout="paged",
                        page_size=page_size, prefill_chunk=page_size,
                        metrics_port=0)
        replicas.append(ReplicaServer(eng, name=f"replica-{i}"))
        eng.start()
    router = Router(replicas, page_size=page_size,
                    affinity_blocks=affinity_blocks,
                    metrics_port=router_port)
    controller = FleetController(
        router, replicas={r.name: r for r in replicas})
    return model, router, replicas, controller


def _stop_fleet(router, replicas):
    router.stop()
    for r in replicas:
        r.engine.stop()


def _build_proc_fleet(args, *, faults_enabled=False):
    """(supervisor, router, controller) over real replica subprocesses."""
    from paddle_tpu.inference.fleet_supervisor import ReplicaSupervisor
    from paddle_tpu.inference.router import FleetController, Router

    sup = ReplicaSupervisor(
        count=args.replicas, model=args.model, page_size=args.page_size,
        slots=args.slots, max_seq_len=args.max_seq_len,
        drain_deadline_s=args.drain_deadline,
        faults_enabled=faults_enabled)
    sup.start()
    if not sup.ready():
        sup.stop()
        raise RuntimeError(
            "fleet failed readiness: "
            + ", ".join(f"{r.name}={r.state}" for r in sup.replicas()))
    router = Router(sup.targets(), page_size=args.page_size,
                    affinity_blocks=args.affinity_blocks,
                    metrics_port=args.port)
    sup.attach(router)
    controller = FleetController(router, restart_hook=sup.restart_replica)
    if router.telemetry is not None:
        router.telemetry.register_json_endpoint(
            "/procz", lambda q: sup.procz())
    return sup, router, controller


def serve_procs(args):
    sup, router, controller = _build_proc_fleet(args)
    print(f"router: http://{router.telemetry.host}:{router.telemetry.port}"
          f"  (/metrics /healthz /routerz /procz /tracez)")
    for rep in sup.replicas():
        print(f"  {rep.name}: http://{rep.target()}  pid={rep.pid}"
              f"  ({args.model} engine)")
    print(f"watch:  python tools/fleetwatch.py --procz "
          f"{router.telemetry.host}:{router.telemetry.port}")
    ticks = 0
    try:
        while args.iterations <= 0 or ticks < args.iterations:
            time.sleep(args.controller_interval)
            acted = controller.tick()
            sup_acted = sup.tick()
            if acted["scale"]:
                sup.apply_scale(acted["scale"])
            ticks += 1
            note = []
            if sup_acted["respawned"]:
                note.append(f"respawned {sup_acted['respawned']}")
            if sup_acted["quarantined"]:
                note.append(f"quarantined {sup_acted['quarantined']}")
            if sup_acted["killed"]:
                note.append(f"killed wedged {sup_acted['killed']}")
            if acted["scale"]:
                note.append(f"scale signal {acted['scale']:+d}")
            state = ",".join(f"{r['name']}={r['state']}(pid {r['pid']})"
                             for r in sup.procz()["replicas"])
            print(f"tick {ticks}: {state}"
                  + (f"  [{'; '.join(note)}]" if note else ""))
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
        esc = sup.stop()
        print(f"fleet stopped ({esc} SIGKILL escalation(s))")
    return 0


def selftest_procs(args):
    """Process-fleet smoke: spawn 2 real replicas, kill one mid-rotation,
    prove witness-backed retry + supervised respawn + scale-up entering
    rotation + bounded zero-escalation shutdown."""
    import signal as _sig

    import numpy as np

    from paddle_tpu.inference.prefix_cache import prefix_key

    args.replicas = 2
    sup, router, controller = _build_proc_fleet(args)
    ok = False
    try:
        rng = np.random.RandomState(11)
        prompt = rng.randint(0, 1024, 24).astype(np.int32)

        # 1. route through real subprocesses; both replicas share the
        #    seed, so the same prompt must yield the same tokens anywhere
        toks0 = router.request(prompt, max_new_tokens=3)
        assert len(toks0) == 3, toks0
        landed = router.affinity.get(
            prefix_key(prompt, args.page_size, blocks=args.affinity_blocks))
        victim = sup.get(landed)
        pid0 = victim.pid

        # 2. SIGKILL the affine replica; the very next request hits the
        #    corpse, the death witness proves the process is gone, and the
        #    router re-routes retry-safely with identical tokens
        os.kill(pid0, _sig.SIGKILL)
        victim.proc.wait(timeout=30)
        toks1 = router.request(prompt, max_new_tokens=3)
        assert toks1 == toks0, (toks1, toks0)

        # 3. the supervisor notices, backs off, respawns a fresh pid
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            sup.tick()
            if victim.state == "ready" and victim.pid != pid0:
                break
            time.sleep(0.1)
        assert victim.state == "ready" and victim.pid != pid0, \
            f"victim not respawned: {victim.to_dict()}"
        router.poll()
        toks2 = router.request(prompt, max_new_tokens=3)
        assert toks2 == toks0, (toks2, toks0)

        # 4. scale-up actually spawns a process and enters rotation
        newcomer = sup.apply_scale(+1)
        assert newcomer is not None
        assert sup.get(newcomer).state == "ready"
        assert any(r["name"] == newcomer
                   for r in router.routerz()["replicas"]), "not in rotation"

        # 5. procz renders (what fleetwatch --procz shows)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import fleetwatch

        table = fleetwatch.render_procz(sup.procz())
        assert landed in table and newcomer in table
        print(table)

        # 6. bounded graceful shutdown: everyone drains inside the
        #    deadline, zero SIGKILL escalations
        router.stop()
        esc = sup.stop()
        assert esc == 0, f"{esc} unexpected SIGKILL escalation(s)"
        ok = True
        print(f"fleetserve --procs selftest: ok (pid {pid0} killed, "
              f"respawned as pid {victim.pid}, inc {victim.incarnation}; "
              f"scaled up {newcomer}; 0 escalations)")
        return 0
    finally:
        if not ok:
            try:
                router.stop()
            finally:
                sup.stop()


def serve(args):
    model, router, replicas, controller = _build_fleet(
        args.replicas, args.page_size, args.slots, args.max_seq_len,
        args.port, args.affinity_blocks)
    print(f"router: http://{router.telemetry.host}:{router.telemetry.port}"
          f"  (/metrics /healthz /routerz /tracez)")
    for r in replicas:
        print(f"  {r.name}: {r.url}  (/admitz /pollz /cancelz)")
    print(f"watch:  python tools/fleetwatch.py --routerz "
          f"{router.telemetry.host}:{router.telemetry.port}")
    ticks = 0
    try:
        while args.iterations <= 0 or ticks < args.iterations:
            time.sleep(args.controller_interval)
            acted = controller.tick()
            ticks += 1
            note = []
            if acted["restarts"]:
                note.append(f"restarted {acted['restarts']}")
            if acted["quarantines"]:
                note.append(f"quarantined {acted['quarantines']}")
            if acted["scale"]:
                note.append(f"scale signal {acted['scale']:+d}")
            state = ",".join(f"{r['name']}={r['state']}"
                             for r in router.routerz()["replicas"])
            print(f"tick {ticks}: {state}"
                  + (f"  [{'; '.join(note)}]" if note else ""))
    except KeyboardInterrupt:
        pass
    finally:
        _stop_fleet(router, replicas)
    return 0


def selftest():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.prefix_cache import prefix_key

    model, router, replicas, controller = _build_fleet(
        n_replicas=2, page_size=16, slots=2, max_seq_len=128,
        router_port=0, affinity_blocks=4)
    try:
        rng = np.random.RandomState(11)
        head = rng.randint(0, 1024, 32).astype(np.int32)
        prompts = [np.concatenate(
            [head, rng.randint(0, 1024, 8).astype(np.int32)])
            for _ in range(4)]

        def oracle(p, n):
            ids = paddle.to_tensor(np.asarray(p, np.int32)[None, :])
            return list(np.asarray(model.generate(
                ids, max_new_tokens=n)._value)[0])

        # 1. live wire path: exact tokens + affinity convergence
        for p in prompts:
            assert router.request(p, max_new_tokens=4) == oracle(p, 4), \
                "routed tokens diverged from the solo-engine oracle"
        rz = router.routerz()
        assert rz["affinity"]["hits"] == len(prompts) - 1, rz["affinity"]
        assert rz["affinity"]["entries"] == 1

        # 2. drain shifts traffic, zero loss, /healthz flips
        landed = router.affinity.get(prefix_key(prompts[0], 16, blocks=4))
        victim = next(r for r in replicas if r.name == landed)
        healthy = next(r for r in replicas if r.name != landed)
        assert victim.drain(timeout=60) is True
        router.poll()
        states = {r["name"]: r["state"]
                  for r in router.routerz()["replicas"]}
        assert states[victim.name] == "draining", states
        assert router.request(prompts[0], max_new_tokens=3) \
            == oracle(prompts[0], 3)
        assert router.affinity.get(
            prefix_key(prompts[0], 16, blocks=4)) == healthy.name
        victim.engine.resume()

        # 3. controller tick is quiet on a healthy fleet
        acted = controller.tick()
        assert acted["restarts"] == [] and acted["quarantines"] == []

        # 4. the routerz document renders (what --routerz shows)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import fleetwatch

        table = fleetwatch.render_routerz(router.routerz())
        assert "replica-0" in table and "affinity:" in table
        print(table)
        print(f"fleetserve selftest: ok ({len(prompts)} routed requests, "
              f"affinity hits {rz['affinity']['hits']}, drain + failback)")
        return 0
    finally:
        _stop_fleet(router, replicas)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--port", type=int, default=0,
                    help="router telemetry port (0 = ephemeral)")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--slots", type=int, default=2,
                    help="max_batch_slots per replica")
    ap.add_argument("--max-seq-len", type=int, default=128)
    ap.add_argument("--affinity-blocks", type=int, default=4)
    ap.add_argument("--controller-interval", type=float, default=5.0)
    ap.add_argument("--iterations", type=int, default=0,
                    help="stop the controller loop after N ticks "
                         "(0 = run until interrupted)")
    ap.add_argument("--procs", action="store_true",
                    help="spawn replicas as real replica_main "
                         "subprocesses under a ReplicaSupervisor")
    ap.add_argument("--model", choices=("tiny", "stub"), default="tiny",
                    help="--procs replica engine: tiny Llama or the "
                         "deterministic no-JAX stub")
    ap.add_argument("--drain-deadline", type=float, default=5.0,
                    help="--procs per-replica drain bound before "
                         "SIGKILL escalation")
    ap.add_argument("--selftest", action="store_true")
    args = ap.parse_args(argv)
    if args.procs:
        return selftest_procs(args) if args.selftest else serve_procs(args)
    if args.selftest:
        return selftest()
    return serve(args)


if __name__ == "__main__":
    sys.exit(main())
